"""Racewatch (Eraser lockset detector) tests: the seeded two-thread write
race is caught with both access stacks, benign lock-protected and
read-only sharing stay quiet, the overhead bounds (sampling knob, per-
field access cap) work, the opt-out env spelling works — and one
regression test per race the gate found in the real package (ISSUE 13
satellite) seeds the PRE-FIX interleaving on a replica and proves the
fixed shape is clean.

Standalone RaceWatch instances (their own LockWatch, no access filter)
are used throughout so the suite never touches the global patch."""
import threading

import pytest

from karpenter_core_tpu.testing import lockwatch, racewatch


def make_watch(**kw):
    lw = lockwatch.LockWatch()
    kw.setdefault("class_filter", lambda cls: True)
    rw = racewatch.RaceWatch(lock_watch=lw, **kw)
    return lw, rw


def run_threads(*fns):
    ts = [
        threading.Thread(target=fn, daemon=True, name=f"rw-{i}")
        for i, fn in enumerate(fns)
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=10)
        assert not t.is_alive()


def alternate(fn_a, fn_b, rounds=20):
    """Run fn_a/fn_b strictly alternating from two live threads (ping-
    pong events): the state machine needs GENUINE interleaving — two
    tiny loops often run to completion sequentially under the GIL, which
    is a synchronized handoff, not a race."""
    ev_a, ev_b = threading.Event(), threading.Event()
    ev_a.set()

    def loop(fn, my_ev, other_ev):
        for _ in range(rounds):
            assert my_ev.wait(10)
            my_ev.clear()
            fn()
            other_ev.set()

    run_threads(
        lambda: loop(fn_a, ev_a, ev_b), lambda: loop(fn_b, ev_b, ev_a)
    )


class Counter:
    def __init__(self, lw):
        self._mu = lw.make_lock("counter-mu")
        self.guarded = 0
        self.racy = 0
        self.read_only = 42


# -- detection ------------------------------------------------------------


def test_seeded_two_thread_write_race_is_detected():
    lw, rw = make_watch(access_cap=0)
    c = Counter(lw)
    rw.track_instance(c)

    def write_once():
        c.racy += 1

    alternate(write_once, write_once)
    races = rw.races()
    assert [r.key for r in races] == ["Counter.racy"]
    report = rw.report()
    assert "candidate data race" in report
    assert "Counter.racy" in report
    # both access stacks are rendered (prior + current)
    assert "prior:" in report and "current:" in report
    assert "no locks" in report


def test_benign_lock_protected_counter_is_clean():
    lw, rw = make_watch(access_cap=0)
    c = Counter(lw)
    rw.track_instance(c)

    def locked_writer():
        for _ in range(200):
            with c._mu:
                c.guarded += 1

    run_threads(locked_writer, locked_writer)
    assert rw.races() == [], rw.report()
    assert "no candidate data races" in rw.report()


def test_read_only_sharing_never_reports():
    """Initialized-then-read-everywhere state is the SHARED state: an
    empty lockset there must not report (Eraser's read-share refinement)."""
    lw, rw = make_watch(access_cap=0)
    c = Counter(lw)
    rw.track_instance(c)
    sink = []

    def reader():
        for _ in range(100):
            sink.append(c.read_only)

    run_threads(reader, reader)
    assert rw.races() == [], rw.report()


def test_single_thread_use_stays_exclusive():
    lw, rw = make_watch(access_cap=0)
    c = Counter(lw)
    rw.track_instance(c)
    for _ in range(50):
        c.racy += 1  # construction thread only: EXCLUSIVE, never reported
    assert rw.races() == []


def test_write_under_different_locks_is_a_race():
    """Lock identity matters: two sibling locks from one site do not
    protect the same field."""
    lw, rw = make_watch(access_cap=0)

    class Split:
        def __init__(self):
            self.a = lw.make_lock("split-site")
            self.b = lw.make_lock("split-site")
            self.field = 0

    s = Split()
    rw.track_instance(s)

    def via_a():
        with s.a:
            s.field += 1

    def via_b():
        with s.b:
            s.field += 1

    alternate(via_a, via_b)
    assert [r.key for r in rw.races()] == ["Split.field"]


def test_suppression_is_counted_not_reported():
    lw, rw = make_watch(access_cap=0)
    rw.suppress("Counter.racy", "seeded benign race for the test")
    c = Counter(lw)
    rw.track_instance(c)

    def write_once():
        c.racy += 1

    alternate(write_once, write_once)
    assert rw.races() == []
    assert rw.stats()["suppressed_hits"].get("Counter.racy", 0) >= 1


# -- overhead bounds ------------------------------------------------------


def test_access_cap_freezes_a_field():
    lw, rw = make_watch(access_cap=10)
    c = Counter(lw)
    rw.track_instance(c)
    for _ in range(100):
        c.guarded += 1  # single-thread: 200 would-be accesses, cap 10
    assert rw.stats()["recorded_accesses"] <= 10 * 4  # per-FIELD cap


def test_race_after_cap_is_not_reported():
    """The cap is a real bound: once a field freezes, later accesses (even
    racy ones) record nothing — the race-smoke lane runs cap-off for
    exhaustiveness."""
    lw, rw = make_watch(access_cap=5)
    c = Counter(lw)
    rw.track_instance(c)
    for _ in range(10):
        c.racy += 1  # burn the cap single-threaded

    def writer():
        for _ in range(50):
            c.racy += 1

    run_threads(writer, writer)
    assert rw.races() == []


def test_sampling_knob_tracks_every_nth_instance():
    lw, rw = make_watch(sample=3, access_cap=0)
    rw.install()

    class Sampled:
        def __init__(self):
            self.mu = lw.make_lock("sampled-mu")

    objs = [Sampled() for _ in range(9)]
    assert len(objs) == 9
    assert rw.stats()["tracked_instances"] == 3  # every 3rd allocation


def test_subclass_of_instrumented_base_is_not_double_wrapped():
    """A subclass inheriting an instrumented base's wrappers must not be
    wrapped again: chained wrappers record every access twice, burning
    the per-field cap at 2x and pinning the base's wrapper permanently."""
    lw, rw = make_watch(access_cap=0)

    class Base:
        def __init__(self):
            self.mu = lw.make_lock("base-mu")
            self.x = 0

    class Child(Base):
        def __init__(self):
            super().__init__()
            self.extra = lw.make_lock("child-mu")

    rw.install()
    b = Base()
    rw.track_instance(b)
    c = Child()  # allocates a lock -> discovery fires for Child too
    rw.track_instance(c)
    assert Base in rw._instrumented
    assert Child not in rw._instrumented  # inherits Base's wrapper: enough
    before = rw.stats()["recorded_accesses"]
    c.x = 1
    after = rw.stats()["recorded_accesses"]
    assert after - before == 1, "chained wrappers double-recorded a write"
    rw.uninstall()
    assert type(c).__setattr__ is object.__setattr__


def test_uninstall_restores_attribute_protocol():
    lw, rw = make_watch(access_cap=0)

    class Plain:
        def __init__(self):
            self.mu = lw.make_lock("plain-mu")
            self.x = 0

    rw.install()
    p = Plain()
    rw.track_instance(p)
    assert type(p).__setattr__ is not object.__setattr__
    rw.uninstall()
    assert type(p).__setattr__ is object.__setattr__
    p.x = 1  # inert: no recording, no error
    assert rw.stats()["tracked_instances"] in (0, 1)


# -- arming ---------------------------------------------------------------


def test_arm_opt_out_spellings():
    assert racewatch.arm("0") is False
    assert racewatch.arm("off", default_on=True) is False
    assert racewatch.arm("", default_on=False) is False


def test_arm_parses_sample_and_cap():
    prev_sample, prev_cap = racewatch.GLOBAL.sample, racewatch.GLOBAL.access_cap
    try:
        assert racewatch.arm("1", default_on=False, sample="4", cap="0") is True
        assert racewatch.GLOBAL.sample == 4
        assert racewatch.GLOBAL.access_cap == 0
    finally:
        racewatch.GLOBAL.sample = prev_sample
        racewatch.GLOBAL.access_cap = prev_cap


# -- regression: the races the gate found in the real package -------------
#
# Each replica seeds the PRE-FIX interleaving shape and must be caught;
# the paired "fixed" replica uses the landed locking discipline and must
# be clean. The real classes are covered by the armed suite-wide watcher
# (conftest pytest_sessionfinish), which fails the whole run if any of
# these regresses in the package itself.


def _seed(watch, obj, interleave_a, interleave_b):
    watch.track_instance(obj)
    alternate(interleave_a, interleave_b)
    return [r.key for r in watch.races()]


def test_regression_host_metadata_prefix_shape():
    """solver/host.py pre-fix: _spawn_locked mutated generation under the
    dispatch lock while report() read it lock-free."""
    lw, rw = make_watch(access_cap=0)

    class HostReplica:
        def __init__(self):
            self._mu = lw.make_lock("host-mu")
            self._meta_mu = lw.make_lock("host-meta-mu")
            self.generation = 0

        def spawn_prefix(self):  # pre-fix: metadata under the DISPATCH lock
            with self._mu:
                self.generation += 1

        def report_prefix(self):  # pre-fix: lock-free read
            return self.generation

        def spawn_fixed(self):
            with self._mu:
                with self._meta_mu:
                    self.generation += 1

        def report_fixed(self):
            with self._meta_mu:
                return self.generation

    h = HostReplica()
    keys = _seed(rw, h, h.spawn_prefix, h.report_prefix)
    assert "HostReplica.generation" in keys

    lw2, rw2 = make_watch(access_cap=0)
    # rebind the replica's locks to the fresh watch
    h2 = HostReplica.__new__(HostReplica)
    h2._mu = lw2.make_lock("host-mu")
    h2._meta_mu = lw2.make_lock("host-meta-mu")
    h2.generation = 0
    rw2.track_instance(h2)
    alternate(h2.spawn_fixed, h2.report_fixed)
    assert rw2.races() == [], rw2.report()


def test_regression_fallback_last_hb_shape():
    """solver/fallback.py pre-fix: _primary_solve wrote _last_hb bare
    while health_report read it under the verdict lock."""
    lw, rw = make_watch(access_cap=0)

    class FallbackReplica:
        def __init__(self):
            self._state_mu = lw.make_lock("state-mu")
            self._last_hb = None

        def solve_prefix(self, hb):
            self._last_hb = hb  # pre-fix: bare write

        def solve_fixed(self, hb):
            with self._state_mu:
                self._last_hb = hb

        def report(self):
            with self._state_mu:
                return self._last_hb

    f = FallbackReplica()
    keys = _seed(rw, f, lambda: f.solve_prefix(object()), f.report)
    assert "FallbackReplica._last_hb" in keys

    lw2, rw2 = make_watch(access_cap=0)
    f2 = FallbackReplica.__new__(FallbackReplica)
    f2._state_mu = lw2.make_lock("state-mu")
    f2._last_hb = None
    rw2.track_instance(f2)
    alternate(lambda: f2.solve_fixed(object()), f2.report)
    assert rw2.races() == [], rw2.report()


def test_regression_provisioner_retry_counter_shape():
    """controllers/provisioning pre-fix: _launch_retry_failures mutated
    with no lock from overlapping reconciles (the class owned _mu but
    never used it)."""
    lw, rw = make_watch(access_cap=0)

    class ProvisionerReplica:
        def __init__(self):
            self._mu = lw.make_lock("prov-mu")
            self.failures = 0

        def reconcile_prefix(self):
            self.failures += 1  # pre-fix: _mu exists but is never held

        def reconcile_fixed(self):
            with self._mu:
                self.failures += 1

    p = ProvisionerReplica()
    keys = _seed(rw, p, p.reconcile_prefix, p.reconcile_prefix)
    assert "ProvisionerReplica.failures" in keys

    lw2, rw2 = make_watch(access_cap=0)
    p2 = ProvisionerReplica.__new__(ProvisionerReplica)
    p2._mu = lw2.make_lock("prov-mu")
    p2.failures = 0
    rw2.track_instance(p2)
    alternate(p2.reconcile_fixed, p2.reconcile_fixed)
    assert rw2.races() == [], rw2.report()


def test_real_resilient_solver_interleaving_is_race_free():
    """The landed fix on the REAL class: solves binding heartbeats while
    another thread polls health_report — no candidate race recorded by
    the armed global watcher (skipped when racewatch is off)."""
    import tests.conftest as conftest

    if not getattr(conftest, "RACEWATCH_ARMED", False):
        pytest.skip("global racewatch not armed")
    from karpenter_core_tpu.solver.fallback import ResilientSolver

    class StubSolver:
        def solve(self, *a, **k):
            return "ok"

    rs = ResilientSolver(
        StubSolver(), StubSolver(), prober=lambda: None,
        solve_timeout=5.0, small_batch_work_max=0,
    )
    before = {r.key for r in racewatch.GLOBAL.races()}

    def solver_loop():
        for _ in range(20):
            rs._primary_solve([], {}, {})

    def health_loop():
        for _ in range(20):
            rs.health_report()
            rs.supports_batched_replan

    run_threads(solver_loop, health_loop)
    after = {r.key for r in racewatch.GLOBAL.races()}
    assert not {
        k for k in (after - before) if k.startswith("ResilientSolver.")
    }, racewatch.GLOBAL.report()


def test_real_metrics_registry_interleaving_is_race_free():
    """metrics/registry.py audit (ISSUE 13 satellite): every mutable
    series dict — including the Gauge.replace_all whole-dict swap — is
    read and written under the per-metric lock; interleaving scrapes
    with writers must record no candidate race on the armed watcher."""
    import tests.conftest as conftest

    if not getattr(conftest, "RACEWATCH_ARMED", False):
        pytest.skip("global racewatch not armed")
    from karpenter_core_tpu.metrics.registry import Registry

    reg = Registry()
    gauge = reg.gauge("rw_audit_gauge")
    counter = reg.counter("rw_audit_counter")
    hist = reg.histogram("rw_audit_hist")
    before = {r.key for r in racewatch.GLOBAL.races()}

    def writer():
        gauge.replace_all([(1.0, {"a": "1"}), (2.0, {"a": "2"})])
        counter.inc({"a": "1"})
        hist.observe(0.25)

    def scraper():
        reg.expose()
        gauge.get({"a": "1"})
        hist.percentile(0.99)

    alternate(writer, scraper)
    after = {r.key for r in racewatch.GLOBAL.races()}
    fresh = {
        k for k in (after - before)
        if k.split(".")[0] in ("Registry", "Counter", "Gauge", "Histogram")
    }
    assert not fresh, racewatch.GLOBAL.report()


def test_real_chaos_fault_interleaving_is_race_free():
    import tests.conftest as conftest

    if not getattr(conftest, "RACEWATCH_ARMED", False):
        pytest.skip("global racewatch not armed")
    from karpenter_core_tpu import chaos

    fault = chaos.Fault("test.point", error=None, probability=0.0)
    before = {r.key for r in racewatch.GLOBAL.races()}

    def fire_loop():
        for _ in range(50):
            fault.fire()

    def repr_loop():
        for _ in range(50):
            repr(fault)

    run_threads(fire_loop, repr_loop)
    after = {r.key for r in racewatch.GLOBAL.races()}
    assert not {k for k in (after - before) if k.startswith("Fault.")}, (
        racewatch.GLOBAL.report()
    )
