"""Deprovisioning suite — expiration, drift, emptiness, consolidation rules.

Mirrors reference pkg/controllers/deprovisioning/suite_test.go (32 specs
condensed): candidate gating (initialized/nominated/labels), expiration
ordering, the drift feature gate, empty-node consolidation, consolidation
disable switches, PDB and do-not-evict blocks, spot-to-spot replacement
prohibition, and launch-failure cordon rollback.
"""
import pytest

from karpenter_core_tpu.api import labels as api_labels
from karpenter_core_tpu.api.labels import (
    LABEL_CAPACITY_TYPE,
    LABEL_NODE_INITIALIZED,
    PROVISIONER_NAME_LABEL_KEY,
)
from karpenter_core_tpu.api.settings import Settings, set_current
from karpenter_core_tpu.cloudprovider import fake
from karpenter_core_tpu.kube.objects import (
    LABEL_INSTANCE_TYPE_STABLE,
    LABEL_TOPOLOGY_ZONE,
    LabelSelector,
    PodDisruptionBudget,
    PodDisruptionBudgetSpec,
    PodDisruptionBudgetStatus,
)
from karpenter_core_tpu.operator import new_operator
from karpenter_core_tpu.testing import FakeClock, make_node, make_pod, make_provisioner


@pytest.fixture
def env():
    clock = FakeClock()
    cp = fake.FakeCloudProvider(fake.instance_types(10))
    op = new_operator(cp, settings=Settings(), clock=clock)
    for d in op.deprovisioning.deprovisioners:
        d.validation_ttl = 0.0
    return op, cp, clock


def add_node(op, clock, name, it_name="fake-it-9", cpu="10", ct="on-demand",
             pods=1, pod_labels=None, pod_annotations=None, initialized=True,
             annotations=None, created_at=None, zone="test-zone-1",
             pod_requests=None, pod_owner_kind="", pod_spread=None):
    """An initialized karpenter node with `pods` bound running pods (shared
    with test_deprovisioning_suite.py)."""
    node = make_node(
        name=name,
        labels={
            PROVISIONER_NAME_LABEL_KEY: "default",
            LABEL_NODE_INITIALIZED: "true" if initialized else "false",
            LABEL_INSTANCE_TYPE_STABLE: it_name,
            LABEL_CAPACITY_TYPE: ct,
            LABEL_TOPOLOGY_ZONE: zone,
        },
        capacity={"cpu": cpu, "memory": "20Gi", "pods": "100"},
    )
    if not initialized:
        del node.metadata.labels[LABEL_NODE_INITIALIZED]
    node.metadata.annotations.update(annotations or {})
    node.metadata.creation_timestamp = created_at if created_at is not None else clock()
    op.kube_client.create(node)
    for i in range(pods):
        pod = make_pod(
            requests=pod_requests or {"cpu": "1"},
            node_name=name,
            unschedulable=False,
            labels=pod_labels,
            annotations=pod_annotations,
            owner_kind=pod_owner_kind,
            topology_spread=pod_spread or [],
        )
        pod.status.phase = "Running"
        op.kube_client.create(pod)
    return node


def provisioner(op, **kwargs):
    p = make_provisioner(name="default", **kwargs)
    op.kube_client.create(p)
    return p


# -- candidate gating -------------------------------------------------------


def test_uninitialized_nodes_are_not_candidates(env):
    op, cp, clock = env
    provisioner(op, consolidation_enabled=True)
    add_node(op, clock, "raw", initialized=False, pods=0)
    op.sync_state()
    assert not op.deprovisioning.reconcile()
    assert op.kube_client.get("Node", "", "raw") is not None


def test_nodes_without_provisioner_label_are_not_candidates(env):
    op, cp, clock = env
    provisioner(op, consolidation_enabled=True)
    node = make_node(name="foreign", capacity={"cpu": "4", "pods": "10"})
    op.kube_client.create(node)
    op.sync_state()
    assert not op.deprovisioning.reconcile()
    assert op.kube_client.get("Node", "", "foreign") is not None


def test_do_not_consolidate_annotation_blocks(env):
    op, cp, clock = env
    provisioner(op, consolidation_enabled=True)
    add_node(op, clock, "anno", pods=0,
             annotations={api_labels.DO_NOT_CONSOLIDATE_NODE_ANNOTATION_KEY: "true"})
    op.sync_state()
    assert not op.deprovisioning.reconcile()
    assert op.kube_client.get("Node", "", "anno") is not None


def test_consolidation_disabled_no_action(env):
    op, cp, clock = env
    provisioner(op)  # consolidation not enabled, no TTLs
    add_node(op, clock, "idle", pods=0)
    op.sync_state()
    assert not op.deprovisioning.reconcile()
    assert op.kube_client.get("Node", "", "idle") is not None


# -- emptiness / empty-node consolidation -----------------------------------


def test_empty_node_consolidation_deletes_empty(env):
    op, cp, clock = env
    provisioner(op, consolidation_enabled=True)
    add_node(op, clock, "empty-1", pods=0)
    add_node(op, clock, "empty-2", pods=0)
    op.sync_state()
    assert op.deprovisioning.reconcile()
    op.step()  # finalizer pass
    assert op.kube_client.get("Node", "", "empty-1") is None
    assert op.kube_client.get("Node", "", "empty-2") is None


def test_daemonset_pods_do_not_prevent_emptiness(env):
    op, cp, clock = env
    provisioner(op, consolidation_enabled=True)
    node = add_node(op, clock, "daemons-only", pods=0)
    daemon = make_pod(requests={"cpu": "0.1"}, node_name=node.metadata.name,
                      unschedulable=False, owner_kind="DaemonSet")
    daemon.status.phase = "Running"
    op.kube_client.create(daemon)
    op.sync_state()
    assert op.deprovisioning.reconcile()


# -- expiration -------------------------------------------------------------


def test_expiration_ignores_unexpired(env):
    op, cp, clock = env
    provisioner(op, ttl_seconds_until_expired=3600)
    add_node(op, clock, "young", pods=1, pod_labels={"app": "x"})
    op.sync_state()
    assert not op.deprovisioning.reconcile()


def test_expiration_replaces_oldest_first(env):
    op, cp, clock = env
    provisioner(op, ttl_seconds_until_expired=3600)
    add_node(op, clock, "older", pods=1, created_at=clock() - 8000)
    add_node(op, clock, "newer", pods=1, created_at=clock() - 7000)
    op.sync_state()
    assert op.deprovisioning.reconcile()
    op.step()
    # oldest node goes first; the newer one still exists (its capacity absorbs
    # the evicted pod, so expiration deletes without a replacement launch)
    assert op.kube_client.get("Node", "", "older") is None
    assert op.kube_client.get("Node", "", "newer") is not None


# -- drift ------------------------------------------------------------------


def test_drift_requires_feature_gate(env):
    op, cp, clock = env
    set_current(Settings(drift_enabled=False))
    provisioner(op)
    add_node(op, clock, "drifted", pods=0,
             annotations={api_labels.VOLUNTARY_DISRUPTION_ANNOTATION_KEY: "drifted"})
    op.sync_state()
    assert not op.deprovisioning.reconcile()
    assert op.kube_client.get("Node", "", "drifted") is not None


def test_drift_deletes_annotated_node_when_enabled(env):
    op, cp, clock = env
    set_current(Settings(drift_enabled=True))
    try:
        provisioner(op)
        add_node(op, clock, "drifted", pods=0,
                 annotations={api_labels.VOLUNTARY_DISRUPTION_ANNOTATION_KEY: "drifted"})
        op.sync_state()
        assert op.deprovisioning.reconcile()
        op.step()
        assert op.kube_client.get("Node", "", "drifted") is None
    finally:
        set_current(Settings())


def test_node_controller_annotates_drifted(env):
    op, cp, clock = env
    set_current(Settings(drift_enabled=True))
    try:
        provisioner(op)
        op.kube_client.create(make_pod(requests={"cpu": "1"}))
        op.step()
        cp.drifted = True
        op.step()
        node = op.kube_client.list("Node")[0]
        assert node.metadata.annotations.get(
            api_labels.VOLUNTARY_DISRUPTION_ANNOTATION_KEY
        ) == "drifted"
    finally:
        set_current(Settings())


# -- consolidation blocks ---------------------------------------------------


def test_pdb_blocks_consolidation(env):
    op, cp, clock = env
    provisioner(op, consolidation_enabled=True)
    add_node(op, clock, "guarded", pods=1, pod_labels={"app": "guarded"})
    pdb = PodDisruptionBudget(
        spec=PodDisruptionBudgetSpec(selector=LabelSelector(match_labels={"app": "guarded"})),
        status=PodDisruptionBudgetStatus(disruptions_allowed=0),
    )
    pdb.metadata.name = "guard"
    pdb.metadata.namespace = "default"
    op.kube_client.create(pdb)
    op.sync_state()
    assert not op.deprovisioning.reconcile()
    assert op.kube_client.get("Node", "", "guarded") is not None


def test_do_not_evict_pod_blocks_consolidation(env):
    op, cp, clock = env
    provisioner(op, consolidation_enabled=True)
    add_node(op, clock, "pinned", pods=1,
             pod_annotations={api_labels.DO_NOT_EVICT_POD_ANNOTATION_KEY: "true"})
    op.sync_state()
    assert not op.deprovisioning.reconcile()
    assert op.kube_client.get("Node", "", "pinned") is not None


def test_spot_to_spot_replacement_forbidden(env):
    op, cp, clock = env
    provisioner(op, consolidation_enabled=True)
    # one spot node with a single small pod: replacing with a cheaper SPOT
    # node is forbidden (consolidation.go:237-244); deletion is impossible
    # because the pod needs somewhere to go -> no action
    add_node(op, clock, "spot-big", it_name="fake-it-9", cpu="10", ct="spot", pods=1)
    op.sync_state()
    assert not op.deprovisioning.reconcile()
    assert op.kube_client.get("Node", "", "spot-big") is not None


def test_multi_node_consolidation_merges_into_one(env):
    op, cp, clock = env
    provisioner(op, consolidation_enabled=True)
    # two lightly-used nodes collapse into ONE cheaper replacement
    add_node(op, clock, "big-1", it_name="fake-it-9", cpu="10", pods=1)
    add_node(op, clock, "big-2", it_name="fake-it-4", cpu="5", pods=1)
    op.sync_state()
    assert op.deprovisioning.reconcile()
    op.step()
    remaining = op.kube_client.list("Node")
    assert {n.metadata.name for n in remaining}.isdisjoint({"big-1", "big-2"})
    assert len(remaining) == 1
    # the merged machine is strictly cheaper than either original
    it_name = remaining[0].metadata.labels[LABEL_INSTANCE_TYPE_STABLE]
    assert it_name not in ("fake-it-9", "fake-it-4")


def test_single_node_consolidation_deletes_when_pods_fit_elsewhere(env):
    op, cp, clock = env
    provisioner(op, consolidation_enabled=True)
    # the only CANDIDATE is "redundant"; the keeper belongs to a second,
    # non-consolidating provisioner, so it is schedulable capacity but never
    # a candidate — its headroom absorbs the pod and "redundant" is deleted
    op.kube_client.create(make_provisioner(name="static"))
    keeper = make_node(
        name="keeper",
        labels={PROVISIONER_NAME_LABEL_KEY: "static", LABEL_NODE_INITIALIZED: "true"},
        capacity={"cpu": "10", "memory": "20Gi", "pods": "100"},
    )
    op.kube_client.create(keeper)
    add_node(op, clock, "redundant", it_name="fake-it-4", cpu="5", pods=1)
    op.sync_state()
    assert op.deprovisioning.reconcile()
    op.step()
    remaining = {n.metadata.name for n in op.kube_client.list("Node")}
    assert remaining == {"keeper"}


def test_replacement_launch_failure_rolls_back_cordon(env):
    op, cp, clock = env
    provisioner(op, ttl_seconds_until_expired=3600)
    add_node(op, clock, "expired", pods=1, created_at=clock() - 8000)
    op.sync_state()
    cp.next_create_err = RuntimeError("no capacity")
    changed = op.deprovisioning.reconcile()
    node = op.kube_client.get("Node", "", "expired")
    assert node is not None
    assert not node.spec.unschedulable, "cordon must be rolled back on launch failure"


# -- TTL revalidation with a stepping clock ---------------------------------
# (consolidation.go:66, validation.go:63-103 — the 15s window is real here,
# driven by FakeClock.advance from the test thread, not zeroed out)


def _stepping(clock, stop, step=1.0, period=0.005):
    """Advance the fake clock in the background until stop is set."""
    import threading
    import time as _time

    def tick():
        while not stop.is_set():
            clock.advance(step)
            _time.sleep(period)

    t = threading.Thread(target=tick, daemon=True)
    t.start()
    return t


def test_empty_node_ttl_revalidates_with_stepping_clock():
    import threading

    clock = FakeClock(grace=5.0)  # stepper-driven: no auto-jump under CI load
    cp = fake.FakeCloudProvider(fake.instance_types(10))
    op = new_operator(cp, settings=Settings(), clock=clock)
    provisioner(op, consolidation_enabled=True)
    add_node(op, clock, "empty-1", pods=0)
    op.sync_state()
    empty = next(
        d for d in op.deprovisioning.deprovisioners
        if type(d).__name__ == "EmptyNodeConsolidation"
    )
    assert empty.validation_ttl == 15.0  # the real TTL, not a test zero
    start = clock()
    stop = threading.Event()
    stepper = _stepping(clock, stop)
    try:
        cmd = empty.compute_command(
            empty.sort_and_filter_candidates(
                __import__(
                    "karpenter_core_tpu.controllers.deprovisioning.core",
                    fromlist=["candidate_nodes"],
                ).candidate_nodes(
                    op.cluster, op.kube_client, cp, empty.should_deprovision, clock
                )
            )
        )
    finally:
        stop.set()
        stepper.join(timeout=2)
    assert clock() - start >= 15.0, "compute_command must wait out the TTL"
    assert cmd.action == "delete"
    assert [n.metadata.name for n in cmd.nodes_to_remove] == ["empty-1"]


def test_multi_node_ttl_blocks_on_nomination():
    """A node nominated for a pending pod during the validation TTL blocks
    the command (validation.go:70-85)."""
    import threading

    from karpenter_core_tpu.controllers.deprovisioning.core import candidate_nodes

    clock = FakeClock(grace=5.0)  # stepper-driven: no auto-jump under CI load
    cp = fake.FakeCloudProvider(fake.instance_types(10))
    op = new_operator(cp, settings=Settings(), clock=clock)
    provisioner(op, consolidation_enabled=True)
    add_node(op, clock, "under-1", it_name="fake-it-9", cpu="10", pods=1)
    add_node(op, clock, "under-2", it_name="fake-it-9", cpu="10", pods=1)
    op.sync_state()
    multi = next(
        d for d in op.deprovisioning.deprovisioners
        if type(d).__name__ == "MultiNodeConsolidation"
    )
    assert multi.validation_ttl == 15.0
    candidates = multi.sort_and_filter_candidates(
        candidate_nodes(op.cluster, op.kube_client, cp, multi.should_deprovision, clock)
    )
    assert len(candidates) == 2

    nominate_after = clock() + 5.0
    nominated = threading.Event()
    stop = threading.Event()

    def tick():
        import time as _time

        while not stop.is_set():
            clock.advance(1.0)
            if clock() >= nominate_after and not nominated.is_set():
                # a pending pod gets nominated onto a candidate mid-TTL
                op.cluster.nominate_node_for_pod(candidates[0].name)
                nominated.set()
            _time.sleep(0.005)

    stepper = threading.Thread(target=tick, daemon=True)
    stepper.start()
    try:
        cmd = multi.compute_command(candidates)
    finally:
        stop.set()
        stepper.join(timeout=2)
    assert nominated.is_set()
    assert cmd.action == "retry", f"nominated candidate must block, got {cmd.action}"


# -- batched-ladder / host-ladder equivalence --------------------------------
# The TPU replan screens every prefix rung in one vmapped dispatch and, for
# a conclusive 0-new-machine winner, issues the DELETE directly from the
# screen (solver/replan.py; consolidation.py _ladder_batched). These pin
# that shortcut to the host ladder's exact-solve answer on the same state.


class _NoBatchedReplan:
    """Delegating wrapper that hides supports_batched_replan, forcing the
    host per-rung ladder on the same underlying solver."""

    supports_batched_replan = False

    def __init__(self, inner):
        self._inner = inner

    def __getattr__(self, name):
        return getattr(self._inner, name)


def _multi_and_candidates(op, cp, clock):
    from karpenter_core_tpu.controllers.deprovisioning.core import candidate_nodes

    multi = next(
        d for d in op.deprovisioning.deprovisioners
        if type(d).__name__ == "MultiNodeConsolidation"
    )
    multi.validation_ttl = 0.0
    candidates = multi.sort_and_filter_candidates(
        candidate_nodes(op.cluster, op.kube_client, cp, multi.should_deprovision, clock)
    )
    return multi, candidates


def test_batched_ladder_delete_matches_host_ladder():
    from karpenter_core_tpu.solver.tpu_solver import TPUSolver

    clock = FakeClock()
    cp = fake.FakeCloudProvider(fake.instance_types(10))
    op = new_operator(
        cp, settings=Settings(), solver=TPUSolver(max_nodes=64), clock=clock
    )
    provisioner(op, consolidation_enabled=True)
    # a non-candidate keeper (different, non-consolidating provisioner)
    # absorbs every displaced pod, so the winning rung removes ALL 8
    # candidates with ZERO new machines -> the screen's direct-delete fires
    op.kube_client.create(make_provisioner(name="static"))
    keeper = make_node(
        name="keeper",
        labels={PROVISIONER_NAME_LABEL_KEY: "static", LABEL_NODE_INITIALIZED: "true"},
        capacity={"cpu": "10", "memory": "20Gi", "pods": "100"},
    )
    op.kube_client.create(keeper)
    for i in range(8):
        add_node(op, clock, f"lite-{i}", it_name="fake-it-9", cpu="10", pods=1,
                 pod_requests={"cpu": "0.1"})
    op.sync_state()
    multi, candidates = _multi_and_candidates(op, cp, clock)
    assert len(candidates) == 8
    assert multi.provisioning.solver.supports_batched_replan

    cmd_batched = multi.first_n_consolidation_ladder(candidates)
    host_solver = _NoBatchedReplan(multi.provisioning.solver)
    orig = multi.provisioning.solver
    try:
        multi.provisioning.solver = host_solver
        cmd_host = multi.first_n_consolidation_ladder(candidates)
    finally:
        multi.provisioning.solver = orig

    assert cmd_batched.action == "delete"
    assert cmd_host.action == "delete"
    assert {n.metadata.name for n in cmd_batched.nodes_to_remove} == {
        n.metadata.name for n in cmd_host.nodes_to_remove
    }
    assert not cmd_batched.replacement_machines


def test_batched_ladder_replace_still_confirms_exactly():
    """A REPLACE outcome (1 new cheaper machine) must still route through
    the exact confirming solve — price and same-type rules live there."""
    from karpenter_core_tpu.solver.tpu_solver import TPUSolver

    clock = FakeClock()
    cp = fake.FakeCloudProvider(fake.instance_types(10))
    op = new_operator(
        cp, settings=Settings(), solver=TPUSolver(max_nodes=64), clock=clock
    )
    provisioner(op, consolidation_enabled=True)
    # two half-used nodes whose pods need a (cheaper, smaller) single node
    add_node(op, clock, "big-1", it_name="fake-it-9", cpu="10", pods=1)
    add_node(op, clock, "big-2", it_name="fake-it-4", cpu="5", pods=1)
    op.sync_state()
    multi, candidates = _multi_and_candidates(op, cp, clock)
    assert len(candidates) == 2

    cmd = multi.first_n_consolidation_ladder(candidates)
    assert cmd.action == "replace"
    assert len(cmd.replacement_machines) == 1
    # the replacement passed the price filter: strictly cheaper than the sum
    names = {it.name for it in cmd.replacement_machines[0].instance_type_options}
    assert "fake-it-9" not in names


def test_screen_delete_validation_rejection_forces_exact_ladder():
    """A validation rejection of a screen-sourced delete must flip the next
    ladder to exact per-rung confirmation (no screen/exact-disagreement
    retry livelock)."""
    from karpenter_core_tpu.solver.tpu_solver import TPUSolver

    clock = FakeClock()
    cp = fake.FakeCloudProvider(fake.instance_types(10))
    op = new_operator(
        cp, settings=Settings(), solver=TPUSolver(max_nodes=64), clock=clock
    )
    provisioner(op, consolidation_enabled=True)
    op.kube_client.create(make_provisioner(name="static"))
    keeper = make_node(
        name="keeper",
        labels={PROVISIONER_NAME_LABEL_KEY: "static", LABEL_NODE_INITIALIZED: "true"},
        capacity={"cpu": "10", "memory": "20Gi", "pods": "100"},
    )
    op.kube_client.create(keeper)
    for i in range(4):
        add_node(op, clock, f"lite-{i}", it_name="fake-it-9", cpu="10", pods=1,
                 pod_requests={"cpu": "0.1"})
    op.sync_state()
    multi, candidates = _multi_and_candidates(op, cp, clock)

    cmd = multi.first_n_consolidation_ladder(candidates)
    assert cmd.action == "delete" and getattr(cmd, "from_screen", False)

    # validation rejects the screen-sourced delete -> RETRY + exact-mode flag
    multi.validate_after_ttl = lambda _cmd: False
    retry = multi.compute_command(candidates)
    assert retry.action == "retry"
    assert multi._confirm_deletes_once

    # next ladder runs the exact confirming path: same delete, no screen tag
    cmd2 = multi.first_n_consolidation_ladder(candidates)
    assert cmd2.action == "delete"
    assert not getattr(cmd2, "from_screen", False)
    assert not multi._confirm_deletes_once  # one-shot, hot path restored
    assert {n.metadata.name for n in cmd2.nodes_to_remove} == {
        n.metadata.name for n in cmd.nodes_to_remove
    }
