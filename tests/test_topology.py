"""Topology suite — spread / pod-affinity / pod-anti-affinity semantics.

Mirrors reference pkg/controllers/provisioning/scheduling/topology_test.go
(73 specs condensed to the behavior-distinct ones): zonal/hostname/
capacity-type spread with kube-scheduler skew rules, provisioner-constrained
domains, existing-pod domain counting, ScheduleAnyway relaxation, node-filter
limiting, self-affinity, namespace filtering, inverse anti-affinity, and
provisioner taint generation.
"""
import pytest

from karpenter_core_tpu.api.labels import (
    CAPACITY_TYPE_ON_DEMAND,
    CAPACITY_TYPE_SPOT,
    LABEL_CAPACITY_TYPE,
    PROVISIONER_NAME_LABEL_KEY,
)
from karpenter_core_tpu.cloudprovider import fake
from karpenter_core_tpu.controllers.provisioning.scheduling.scheduler import (
    SchedulerOptions,
    build_scheduler,
)
from karpenter_core_tpu.kube.client import InMemoryKubeClient
from karpenter_core_tpu.kube.objects import (
    LABEL_HOSTNAME,
    LABEL_TOPOLOGY_ZONE,
    LabelSelector,
    LabelSelectorRequirement,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    PodAffinityTerm,
    Taint,
    Toleration,
    TopologySpreadConstraint,
)
from karpenter_core_tpu.testing import make_node, make_pod, make_provisioner

WEB = {"app": "web"}


def spread(key=LABEL_TOPOLOGY_ZONE, max_skew=1, selector=WEB, unsat="DoNotSchedule"):
    return TopologySpreadConstraint(
        max_skew=max_skew,
        topology_key=key,
        when_unsatisfiable=unsat,
        label_selector=LabelSelector(match_labels=selector) if selector is not None else None,
    )


def solve(pods, provisioners=None, instance_types=None, state_nodes=None, kube=None,
          cluster=None):
    provisioners = provisioners or [make_provisioner(name="default")]
    its = instance_types if instance_types is not None else fake.instance_types(10)
    it_map = {p.name: its for p in provisioners}
    scheduler = build_scheduler(
        kube or InMemoryKubeClient(),
        cluster,
        provisioners,
        it_map,
        pods,
        state_nodes=state_nodes,
        opts=SchedulerOptions(simulation_mode=True),
    )
    return scheduler.solve(pods)


def skew(result, key):
    """Pods per committed domain over new machines (ExpectSkew analog)."""
    counts = {}
    for m in result.new_machines:
        if not m.pods:
            continue
        req = m.requirements.get_requirement(key)
        assert req.len() == 1, f"domain not committed for {key}: {req!r}"
        domain = req.values_list()[0]
        counts[domain] = counts.get(domain, 0) + len(m.pods)
    return counts


# -- spread basics ----------------------------------------------------------


def test_unknown_topology_key_fails_pod_but_not_others():
    """topology_test.go:39-56."""
    pods = [
        make_pod(labels=WEB, topology_spread=[spread(key="unknown")]),
        make_pod(),
    ]
    result = solve(pods)
    assert len(result.failed_pods) == 1
    assert result.pod_count_new() == 1


def test_zonal_spread_match_expressions():
    """topology_test.go:87-110."""
    sel = LabelSelector(
        match_expressions=[LabelSelectorRequirement(key="app", operator="In", values=["web"])]
    )
    constraint = TopologySpreadConstraint(
        max_skew=1, topology_key=LABEL_TOPOLOGY_ZONE,
        when_unsatisfiable="DoNotSchedule", label_selector=sel,
    )
    pods = [make_pod(labels=WEB, requests={"cpu": "1"}, topology_spread=[constraint])
            for _ in range(6)]
    result = solve(pods)
    assert not result.failed_pods
    assert sorted(skew(result, LABEL_TOPOLOGY_ZONE).values()) == [2, 2, 2]


def test_spread_respects_provisioner_zone_subset():
    """topology_test.go:129-147: provisioner limited to 2 zones -> spread
    balances across exactly those."""
    prov = make_provisioner(
        name="default",
        requirements=[NodeSelectorRequirement(
            LABEL_TOPOLOGY_ZONE, "In", ["test-zone-1", "test-zone-2"]
        )],
    )
    pods = [make_pod(labels=WEB, requests={"cpu": "1"}, topology_spread=[spread()])
            for _ in range(4)]
    result = solve(pods, provisioners=[prov])
    assert not result.failed_pods
    counts = skew(result, LABEL_TOPOLOGY_ZONE)
    assert sorted(counts.values()) == [2, 2]
    assert set(counts) == {"test-zone-1", "test-zone-2"}


def test_spread_counts_existing_cluster_pods():
    """topology_test.go:148-186: domain counts seed from pods already bound
    to nodes (countDomains, topology.go:231-276)."""
    kube = InMemoryKubeClient()
    node = make_node(labels={LABEL_TOPOLOGY_ZONE: "test-zone-1"})
    kube.create(node)
    bound = make_pod(labels=WEB, node_name=node.metadata.name, unschedulable=False,
                     phase="Running")
    kube.create(bound)
    pods = [make_pod(labels=WEB, requests={"cpu": "1"}, topology_spread=[spread()])
            for _ in range(2)]
    result = solve(pods, kube=kube)
    assert not result.failed_pods
    counts = skew(result, LABEL_TOPOLOGY_ZONE)
    # zone-1 already has 1: the two new pods land in zone-2 and zone-3
    assert counts == {"test-zone-2": 1, "test-zone-3": 1}


def test_spread_prefers_minimum_domains_when_skewed():
    """topology_test.go:229-267: with zone-1 over-count, new pods go to the
    minimum domains first."""
    kube = InMemoryKubeClient()
    node = make_node(labels={LABEL_TOPOLOGY_ZONE: "test-zone-1"})
    kube.create(node)
    for _ in range(3):
        kube.create(make_pod(labels=WEB, node_name=node.metadata.name,
                             unschedulable=False, phase="Running"))
    result = solve([make_pod(labels=WEB, requests={"cpu": "1"}, topology_spread=[spread()])],
                   kube=kube)
    assert not result.failed_pods
    assert set(skew(result, LABEL_TOPOLOGY_ZONE)) <= {"test-zone-2", "test-zone-3"}


def test_spread_do_not_schedule_blocks_over_skew():
    """topology_test.go:268-300: zone-1 seeded with 1 pod; provisioner then
    restricted to zones 2/3 -> only 4 more pods fit under maxSkew 1."""
    kube = InMemoryKubeClient()
    node = make_node(labels={LABEL_TOPOLOGY_ZONE: "test-zone-1"})
    kube.create(node)
    kube.create(make_pod(labels=WEB, node_name=node.metadata.name,
                         unschedulable=False, phase="Running"))
    prov = make_provisioner(
        name="default",
        requirements=[NodeSelectorRequirement(
            LABEL_TOPOLOGY_ZONE, "In", ["test-zone-2", "test-zone-3"]
        )],
    )
    pods = [make_pod(labels=WEB, requests={"cpu": "1"}, topology_spread=[spread()])
            for _ in range(10)]
    result = solve(pods, provisioners=[prov], kube=kube)
    # max skew 1 over counts {z1:1}: z2/z3 can take 2 each, the rest fail
    counts = skew(result, LABEL_TOPOLOGY_ZONE)
    assert counts == {"test-zone-2": 2, "test-zone-3": 2}
    assert len(result.failed_pods) == 6


def test_capacity_type_spread_balances():
    """topology_test.go:520-535."""
    pods = [make_pod(labels=WEB, requests={"cpu": "1"},
                     topology_spread=[spread(key=LABEL_CAPACITY_TYPE)])
            for _ in range(4)]
    result = solve(pods)
    assert not result.failed_pods
    counts = skew(result, LABEL_CAPACITY_TYPE)
    assert sorted(counts.values()) == [2, 2]
    assert set(counts) == {CAPACITY_TYPE_SPOT, CAPACITY_TYPE_ON_DEMAND}


def test_schedule_anyway_spread_violated_when_unsatisfiable():
    """topology_test.go:589-619: ScheduleAnyway spreads are dropped by
    relaxation when the only capacity is one domain."""
    prov = make_provisioner(
        name="default",
        requirements=[NodeSelectorRequirement(LABEL_CAPACITY_TYPE, "In",
                                              [CAPACITY_TYPE_SPOT])],
    )
    kube = InMemoryKubeClient()
    node = make_node(labels={LABEL_CAPACITY_TYPE: CAPACITY_TYPE_SPOT})
    kube.create(node)
    for _ in range(2):
        kube.create(make_pod(labels=WEB, node_name=node.metadata.name,
                             unschedulable=False, phase="Running"))
    pods = [make_pod(labels=WEB, requests={"cpu": "1"},
                     topology_spread=[spread(key=LABEL_CAPACITY_TYPE, unsat="ScheduleAnyway")])
            for _ in range(3)]
    result = solve(pods, provisioners=[prov], kube=kube)
    assert not result.failed_pods  # violation allowed after relaxation
    assert result.pod_count_new() == 3


def test_hostname_spread_max_skew_two_packs_pairs():
    """topology_test.go:422-437: maxSkew 2 on hostname lets pods double up."""
    pods = [make_pod(labels=WEB, requests={"cpu": "1"},
                     topology_spread=[spread(key=LABEL_HOSTNAME, max_skew=2)])
            for _ in range(4)]
    result = solve(pods, instance_types=fake.instance_types(5))
    assert not result.failed_pods
    per_machine = sorted(len(m.pods) for m in result.new_machines if m.pods)
    assert max(per_machine) <= 2
    assert len(per_machine) >= 2


def test_combined_zone_and_hostname_spread():
    """topology_test.go:814-853."""
    pods = [
        make_pod(labels=WEB, requests={"cpu": "1"},
                 topology_spread=[spread(), spread(key=LABEL_HOSTNAME)])
        for _ in range(6)
    ]
    result = solve(pods, instance_types=fake.instance_types(5))
    assert not result.failed_pods
    assert sorted(skew(result, LABEL_TOPOLOGY_ZONE).values()) == [2, 2, 2]
    # hostname spread with skew 1: one pod per machine
    assert all(len(m.pods) <= 1 for m in result.new_machines)


def test_spread_limited_by_node_selector():
    """topology_test.go:1067-1092: a nodeSelector restricts the domains the
    spread can use; all pods land in the selected zone."""
    pods = [
        make_pod(labels=WEB, requests={"cpu": "1"},
                 node_selector={LABEL_TOPOLOGY_ZONE: "test-zone-1"},
                 topology_spread=[spread()])
        for _ in range(4)
    ]
    result = solve(pods)
    assert not result.failed_pods
    assert set(skew(result, LABEL_TOPOLOGY_ZONE)) == {"test-zone-1"}


def test_interdependent_selectors_pack_freely():
    """topology_test.go:378-405: pods whose spread selector matches nothing
    don't count toward skew, so they may pack onto one node."""
    pods = [make_pod(requests={"cpu": "1"},
                     topology_spread=[spread(key=LABEL_HOSTNAME)])
            for _ in range(5)]
    result = solve(pods, instance_types=fake.instance_types(20))
    assert not result.failed_pods
    assert len([m for m in result.new_machines if m.pods]) == 1


def test_nil_selector_spread_schedules():
    """topology_test.go:366-377: a nil labelSelector selects nothing; the pod
    still schedules."""
    result = solve([make_pod(topology_spread=[spread(selector=None)])])
    assert not result.failed_pods


def test_spread_across_multiple_provisioners():
    """topology_test.go:2214-2248: the domain universe unions across
    provisioners."""
    p1 = make_provisioner(
        name="p1",
        requirements=[NodeSelectorRequirement(LABEL_TOPOLOGY_ZONE, "In", ["test-zone-1"])],
    )
    p2 = make_provisioner(
        name="p2",
        requirements=[NodeSelectorRequirement(LABEL_TOPOLOGY_ZONE, "In",
                                              ["test-zone-2", "test-zone-3"])],
    )
    pods = [make_pod(labels=WEB, requests={"cpu": "1"}, topology_spread=[spread()])
            for _ in range(3)]
    result = solve(pods, provisioners=[p1, p2])
    assert not result.failed_pods
    assert sorted(skew(result, LABEL_TOPOLOGY_ZONE).values()) == [1, 1, 1]


# -- pod affinity -----------------------------------------------------------


def test_empty_affinity_schedules():
    """topology_test.go:1232-1241."""
    pod = make_pod(pod_affinity_required=[], pod_anti_affinity_required=[])
    result = solve([pod])
    assert not result.failed_pods


def test_self_affinity_hostname_colocates():
    """topology_test.go:1319-1342: pods selecting themselves land together."""
    term = PodAffinityTerm(topology_key=LABEL_HOSTNAME,
                           label_selector=LabelSelector(match_labels=WEB))
    pods = [make_pod(labels=WEB, requests={"cpu": "1"}, pod_affinity_required=[term])
            for _ in range(3)]
    result = solve(pods, instance_types=fake.instance_types(20))
    assert not result.failed_pods
    assert len([m for m in result.new_machines if m.pods]) == 1


def test_affinity_zone_with_seeded_target():
    """topology_test.go:1981-2013: affinity pods follow an existing target's
    zone."""
    kube = InMemoryKubeClient()
    node = make_node(labels={LABEL_TOPOLOGY_ZONE: "test-zone-2"})
    kube.create(node)
    kube.create(make_pod(labels={"app": "target"}, node_name=node.metadata.name,
                         unschedulable=False, phase="Running"))
    term = PodAffinityTerm(topology_key=LABEL_TOPOLOGY_ZONE,
                           label_selector=LabelSelector(match_labels={"app": "target"}))
    pods = [make_pod(requests={"cpu": "1"}, pod_affinity_required=[term]) for _ in range(3)]
    result = solve(pods, kube=kube)
    assert not result.failed_pods
    for m in result.new_machines:
        if m.pods:
            assert m.requirements.get_requirement(LABEL_TOPOLOGY_ZONE).values_list() == [
                "test-zone-2"
            ]


def test_affinity_to_nonexistent_pod_fails():
    """topology_test.go:1964-1980."""
    term = PodAffinityTerm(topology_key=LABEL_TOPOLOGY_ZONE,
                           label_selector=LabelSelector(match_labels={"app": "ghost"}))
    result = solve([make_pod(requests={"cpu": "1"}, pod_affinity_required=[term])])
    assert len(result.failed_pods) == 1


def test_affinity_filtered_by_namespace():
    """topology_test.go:2094-2131: affinity only sees pods in the term's
    namespaces (default: the pod's own)."""
    kube = InMemoryKubeClient()
    node = make_node(labels={LABEL_TOPOLOGY_ZONE: "test-zone-2"})
    kube.create(node)
    kube.create(make_pod(labels={"app": "target"}, namespace="other",
                         node_name=node.metadata.name, unschedulable=False,
                         phase="Running"))
    term = PodAffinityTerm(topology_key=LABEL_TOPOLOGY_ZONE,
                           label_selector=LabelSelector(match_labels={"app": "target"}))
    # pod in "default" can't see the target in "other"
    result = solve([make_pod(requests={"cpu": "1"}, pod_affinity_required=[term])], kube=kube)
    assert len(result.failed_pods) == 1
    # naming the namespace in the term fixes it
    term2 = PodAffinityTerm(topology_key=LABEL_TOPOLOGY_ZONE,
                            label_selector=LabelSelector(match_labels={"app": "target"}),
                            namespaces=["other"])
    result2 = solve([make_pod(requests={"cpu": "1"}, pod_affinity_required=[term2])], kube=kube)
    assert not result2.failed_pods


def test_preferred_affinity_violation_allowed():
    """topology_test.go:1484-1516: preferred pod affinity with no viable
    domain is relaxed away."""
    from karpenter_core_tpu.kube.objects import WeightedPodAffinityTerm

    pref = WeightedPodAffinityTerm(
        weight=50,
        pod_affinity_term=PodAffinityTerm(
            topology_key=LABEL_TOPOLOGY_ZONE,
            label_selector=LabelSelector(match_labels={"app": "ghost"}),
        ),
    )
    result = solve([make_pod(requests={"cpu": "1"}, pod_affinity_preferred=[pref])])
    assert not result.failed_pods


def test_preferred_anti_affinity_violation_allowed():
    """topology_test.go:1517-1549."""
    from karpenter_core_tpu.kube.objects import WeightedPodAffinityTerm

    pref = WeightedPodAffinityTerm(
        weight=50,
        pod_affinity_term=PodAffinityTerm(
            topology_key=LABEL_TOPOLOGY_ZONE,
            label_selector=LabelSelector(match_labels=WEB),
        ),
    )
    pods = [make_pod(labels=WEB, requests={"cpu": "1"}, pod_anti_affinity_preferred=[pref])
            for _ in range(5)]
    result = solve(pods)
    assert not result.failed_pods  # only 3 zones; violations permitted


# -- inverse anti-affinity --------------------------------------------------


class _FakeCluster:
    """Minimal cluster exposing anti-affinity pod->node pairs."""

    def __init__(self, pairs):
        self.pairs = pairs

    def for_pods_with_anti_affinity(self, visit):
        for pod, node in self.pairs:
            if not visit(pod, node):
                return


def test_inverse_anti_affinity_blocks_domain():
    """topology_test.go:1716-1783: an EXISTING pod with anti-affinity against
    app=web blocks new web pods from its zone."""
    term = PodAffinityTerm(topology_key=LABEL_TOPOLOGY_ZONE,
                           label_selector=LabelSelector(match_labels=WEB))
    existing = make_pod(labels={"app": "db"}, node_name="existing-node",
                        unschedulable=False, phase="Running",
                        pod_anti_affinity_required=[term])
    node = make_node(name="existing-node", labels={LABEL_TOPOLOGY_ZONE: "test-zone-3"})
    cluster = _FakeCluster([(existing, node)])
    pods = [make_pod(labels=WEB, requests={"cpu": "1"}) for _ in range(3)]
    result = solve(pods, cluster=cluster)
    assert not result.failed_pods
    for m in result.new_machines:
        if m.pods:
            assert not m.requirements.get_requirement(LABEL_TOPOLOGY_ZONE).has("test-zone-3")


# -- provisioner taints -----------------------------------------------------


def test_provisioner_taints_applied_to_machine():
    """topology_test.go:2250-2259."""
    prov = make_provisioner(name="default",
                            taints=[Taint("example.com/special", "true", "NoSchedule")])
    result = solve(
        [make_pod(requests={"cpu": "1"},
                  tolerations=[Toleration(key="example.com/special", operator="Exists")])],
        provisioners=[prov],
    )
    assert not result.failed_pods
    machine = result.new_machines[0]
    assert any(t.key == "example.com/special" for t in machine.template.taints)


def test_startup_taints_do_not_block_scheduling():
    """topology_test.go:2287-2294: startup taints exist on the node but are
    not considered for pod scheduling."""
    prov = make_provisioner(name="default",
                            startup_taints=[Taint("example.com/init", "true", "NoSchedule")])
    result = solve([make_pod(requests={"cpu": "1"})], provisioners=[prov])
    assert not result.failed_pods
