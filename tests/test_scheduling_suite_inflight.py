"""Port of reference scheduling suite_test.go — In-Flight Nodes describe
(suite_test.go:1254-1828): in-flight reuse, zone/hostname balance against
in-flight nodes, taint assumptions, daemonset accounting, bin-pack-first.
Cited line numbers refer to
/root/reference/pkg/controllers/provisioning/scheduling/suite_test.go.

nodeStateController/podStateController reconciles map to op.sync_state()
(the level-triggered informer relist) and cluster.update_pod.
"""
import pytest

from karpenter_core_tpu.api import labels as api_labels
from karpenter_core_tpu.cloudprovider import fake
from karpenter_core_tpu.kube.objects import (
    LABEL_ARCH_STABLE,
    LABEL_HOSTNAME,
    LABEL_TOPOLOGY_ZONE,
    LabelSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    Taint,
    TopologySpreadConstraint,
)
from karpenter_core_tpu.testing import make_daemonset, make_pod, make_provisioner
from karpenter_core_tpu.testing.expectations import Env

ZONE = LABEL_TOPOLOGY_ZONE


@pytest.fixture()
def env():
    return Env()


def req(key, op, *values):
    return NodeSelectorRequirement(key=key, operator=op, values=list(values))


def terms(*exprs):
    return [NodeSelectorTerm(match_expressions=list(exprs))]


def spread(key=ZONE, selector=None):
    return TopologySpreadConstraint(
        max_skew=1,
        topology_key=key,
        when_unsatisfiable="DoNotSchedule",
        label_selector=LabelSelector(match_labels=selector or {"foo": "bar"}),
    )


def test_reuses_inflight_node_with_capacity(env):
    """suite_test.go:1255-1271."""
    env.expect_applied(make_provisioner(name="default"))
    initial = make_pod(limits={"cpu": "10m"})
    env.expect_provisioned(initial)
    node1 = env.expect_scheduled(initial)
    env.op.sync_state()

    second = make_pod(limits={"cpu": "10m"})
    env.expect_provisioned(second)
    node2 = env.expect_scheduled(second)
    assert node1.metadata.name == node2.metadata.name


def test_reuses_inflight_node_node_selectors(env):
    """suite_test.go:1272-1320 — zone intersection reuses; disjoint opens."""
    env.expect_applied(make_provisioner(name="default"))
    initial = make_pod(
        limits={"cpu": "10m"},
        node_affinity_required=terms(req(ZONE, "In", "test-zone-2")),
    )
    env.expect_provisioned(initial)
    node1 = env.expect_scheduled(initial)
    env.op.sync_state()

    second = make_pod(
        limits={"cpu": "10m"},
        node_affinity_required=terms(req(ZONE, "In", "test-zone-1", "test-zone-2")),
    )
    env.expect_provisioned(second)
    node2 = env.expect_scheduled(second)
    assert node1.metadata.name == node2.metadata.name
    env.op.sync_state()

    third = make_pod(
        limits={"cpu": "10m"},
        node_affinity_required=terms(req(ZONE, "In", "test-zone-1", "test-zone-3")),
    )
    env.expect_provisioned(third)
    node3 = env.expect_scheduled(third)
    assert node1.metadata.name != node3.metadata.name


def test_second_node_when_pod_does_not_fit(env):
    """suite_test.go:1321-1339."""
    env.expect_applied(make_provisioner(name="default"))
    initial = make_pod(limits={"cpu": "1001m"})
    env.expect_provisioned(initial)
    node1 = env.expect_scheduled(initial)
    env.op.sync_state()

    second = make_pod(limits={"cpu": "1"})
    env.expect_provisioned(second)
    node2 = env.expect_scheduled(second)
    assert node1.metadata.name != node2.metadata.name


def test_second_node_when_pod_incompatible_selector(env):
    """suite_test.go:1340-1356."""
    env.expect_applied(make_provisioner(name="default"))
    initial = make_pod(limits={"cpu": "10m"})
    env.expect_provisioned(initial)
    node1 = env.expect_scheduled(initial)
    env.op.sync_state()

    second = make_pod(node_selector={LABEL_ARCH_STABLE: "arm64"})
    env.expect_provisioned(second)
    node2 = env.expect_scheduled(second)
    assert node1.metadata.name != node2.metadata.name


def test_second_node_when_inflight_terminating(env):
    """suite_test.go:1357-1379."""
    env.expect_applied(make_provisioner(name="default"))
    initial = make_pod(limits={"cpu": "10m"})
    env.expect_provisioned(initial)
    node1 = env.expect_scheduled(initial)
    env.op.sync_state()

    env.expect_deleted(node1)
    env.op.sync_state()

    second = make_pod(limits={"cpu": "10m"})
    env.expect_provisioned(second)
    node2 = env.expect_scheduled(second)
    assert node1.metadata.name != node2.metadata.name


# -- Topology with in-flight nodes (suite_test.go:1380-1452) ----------------


def test_balances_zones_with_inflight_nodes(env):
    """suite_test.go:1381-1418."""
    labels = {"foo": "bar"}
    topo = spread()
    env.expect_applied(make_provisioner(name="default"))
    pods = [make_pod(labels=labels, topology_spread=[topo]) for _ in range(4)]
    env.expect_provisioned(*pods)
    assert sorted(env.expect_skew("default", topo).values()) == [1, 1, 2]

    env.op.sync_state()
    first_round_nodes = len(env.kube.list("Node"))
    more = [make_pod(labels=labels, topology_spread=[topo]) for _ in range(5)]
    env.expect_provisioned(*more)
    assert sorted(env.expect_skew("default", topo).values()) == [3, 3, 3]
    # in-flight nodes absorb the second round
    assert len(env.kube.list("Node")) == first_round_nodes


def test_balances_hostnames_with_inflight_nodes(env):
    """suite_test.go:1419-1452 — hostname spread prefers fresh nodes."""
    labels = {"foo": "bar"}
    topo = spread(key=LABEL_HOSTNAME)
    env.expect_applied(make_provisioner(name="default"))
    pods = [make_pod(labels=labels, topology_spread=[topo]) for _ in range(4)]
    env.expect_provisioned(*pods)
    assert sorted(env.expect_skew("default", topo).values()) == [1, 1, 1, 1]

    env.op.sync_state()
    more = [make_pod(labels=labels, topology_spread=[topo]) for _ in range(5)]
    env.expect_provisioned(*more)
    assert sorted(env.expect_skew("default", topo).values()) == [1] * 9


# -- Taints with in-flight nodes (suite_test.go:1453-1588) ------------------


def test_assumes_pod_schedules_to_untainted_node(env):
    """suite_test.go:1454-1475."""
    env.expect_applied(make_provisioner(name="default"))
    initial = make_pod(limits={"cpu": "8"})
    env.expect_provisioned(initial)
    node1 = env.expect_scheduled(initial)

    env.expect_deleted(initial)
    node1.spec.taints = []
    env.expect_applied(node1)
    env.op.sync_state()

    second = make_pod()
    env.expect_provisioned(second)
    node2 = env.expect_scheduled(second)
    assert node1.metadata.name == node2.metadata.name


def test_does_not_assume_pod_schedules_to_tainted_node(env):
    """suite_test.go:1476-1502."""
    env.expect_applied(make_provisioner(name="default"))
    initial = make_pod(limits={"cpu": "8"})
    env.expect_provisioned(initial)
    node1 = env.expect_scheduled(initial)

    env.expect_deleted(initial)
    env.drop_machine(node1)  # raw-node path: the spec taints the Node directly
    node1.spec.taints = list(node1.spec.taints) + [
        Taint(key="foo.com/taint", value="tainted", effect="NoSchedule")
    ]
    env.expect_applied(node1)
    env.op.sync_state()

    second = make_pod()
    env.expect_provisioned(second)
    node2 = env.expect_scheduled(second)
    assert node1.metadata.name != node2.metadata.name


def test_assumes_pod_schedules_through_custom_startup_taint(env):
    """suite_test.go:1503-1535 — startup taints don't block assumption."""
    env.expect_applied(
        make_provisioner(
            name="default",
            startup_taints=[Taint(key="foo.com/taint", value="tainted", effect="NoSchedule")],
        )
    )
    initial = make_pod(limits={"cpu": "8"})
    env.expect_provisioned(initial)
    node1 = env.expect_scheduled(initial)

    env.expect_deleted(initial)
    assert any(t.key == "foo.com/taint" for t in node1.spec.taints)
    env.expect_applied(node1)
    env.op.sync_state()

    second = make_pod()
    env.expect_provisioned(second)
    node2 = env.expect_scheduled(second)
    assert node1.metadata.name == node2.metadata.name


def test_does_not_assume_startup_taint_after_initialization(env):
    """suite_test.go:1536-1561."""
    startup = Taint(key="ignore-me", value="nothing-to-see-here", effect="NoSchedule")
    env.expect_applied(make_provisioner(name="default", startup_taints=[startup]))
    initial = make_pod()
    env.expect_provisioned(initial)
    node1 = env.expect_scheduled(initial)

    env.expect_deleted(initial)
    env.drop_machine(node1)  # raw-node path: initialized label set by hand
    node1.metadata.labels[api_labels.LABEL_NODE_INITIALIZED] = "true"
    node1.spec.taints = [startup]
    node1.status.capacity = {"pods": 10.0}
    env.expect_applied(node1)
    env.op.sync_state()

    second = make_pod()
    env.expect_provisioned(second)
    node2 = env.expect_scheduled(second)
    assert node1.metadata.name != node2.metadata.name


def test_tainted_notready_node_is_inflight_even_if_initialized(env):
    """suite_test.go:1562-1588 — ephemeral not-ready taints are masked."""
    env.expect_applied(make_provisioner(name="default"))
    pod = make_pod(requests={"cpu": "10m"})
    env.expect_provisioned(pod)
    node1 = env.expect_scheduled(pod)
    env.op.sync_state()

    node1.metadata.labels[api_labels.LABEL_NODE_INITIALIZED] = "true"
    node1.spec.taints = [
        Taint(key="node.kubernetes.io/not-ready", effect="NoSchedule"),
        Taint(key="node.kubernetes.io/unreachable", effect="NoSchedule"),
    ]
    env.expect_applied(node1)
    env.op.sync_state()

    pod2 = make_pod(requests={"cpu": "10m"})
    env.expect_provisioned(pod2)
    node2 = env.expect_scheduled(pod2)
    assert node1.metadata.name == node2.metadata.name


# -- Daemonsets with in-flight nodes (suite_test.go:1589-1757) --------------


def test_daemonset_usage_tracked_separately(env):
    """suite_test.go:1590-1663."""
    ds = make_daemonset(requests={"cpu": "1", "memory": "1Gi"})
    env.expect_applied(make_provisioner(name="default"), ds)

    initial = make_pod(limits={"cpu": "8"})
    env.expect_provisioned(initial)
    node1 = env.expect_scheduled(initial)

    ds_pod = make_pod(requests={"cpu": "1", "memory": "2Gi"}, owner_kind="DaemonSet")
    env.expect_deleted(initial)
    env.op.sync_state()
    env.expect_applied(ds_pod)
    for state_node in env.cluster.nodes():
        assert state_node.total_daemonset_requests().get("cpu", 0.0) == pytest.approx(0)
        # full 16 cpu - 100m overhead (the 8-cpu pod forced the arm type)
        assert state_node.available().get("cpu", 0.0) == pytest.approx(15.9)

    env.expect_manual_binding(ds_pod, node1)
    env.op.sync_state()
    for state_node in env.cluster.nodes():
        assert state_node.total_daemonset_requests().get("cpu", 0.0) == pytest.approx(1)
        assert state_node.available().get("cpu", 0.0) == pytest.approx(14.9)

    second = make_pod(limits={"cpu": "14.9"})
    env.expect_provisioned(second)
    node2 = env.expect_scheduled(second)
    assert node1.metadata.name == node2.metadata.name


def test_unexpected_daemonset_pod_binding(env):
    """suite_test.go:1664-1756 — unexpected node label attracting a DS pod
    must not corrupt the remaining-daemonset accounting."""
    ds1 = make_daemonset(
        requests={"cpu": "1", "memory": "1Gi"}, node_selector={"my-node-label": "value"}
    )
    ds2 = make_daemonset(requests={"cpu": "1m"})
    env.expect_applied(make_provisioner(name="default"), ds1, ds2)

    initial = make_pod(limits={"cpu": "8"})
    env.expect_provisioned(initial)
    node1 = env.expect_scheduled(initial)
    node1.metadata.labels["my-node-label"] = "value"
    env.expect_applied(node1)

    ds_pod = make_pod(
        node_selector={"my-node-label": "value"},
        requests={"cpu": "1", "memory": "2Gi"},
        owner_kind="DaemonSet",
    )
    env.expect_deleted(initial)
    env.op.sync_state()
    env.expect_applied(ds_pod)
    for state_node in env.cluster.nodes():
        assert state_node.total_daemonset_requests().get("cpu", 0.0) == pytest.approx(0)
        assert state_node.available().get("cpu", 0.0) == pytest.approx(15.9)

    env.expect_manual_binding(ds_pod, node1)
    env.op.sync_state()
    for state_node in env.cluster.nodes():
        assert state_node.total_daemonset_requests().get("cpu", 0.0) == pytest.approx(1)
        assert state_node.available().get("cpu", 0.0) == pytest.approx(14.9)

    second = make_pod(limits={"cpu": "15.5"})
    env.expect_provisioned(second)
    node2 = env.expect_scheduled(second)
    assert node1.metadata.name != node2.metadata.name


# -- bin-pack-first over batches (suite_test.go:1758-1828) ------------------


def test_packs_inflight_nodes_before_launching_new():
    """suite_test.go:1758-1798 — random batches leave <=1 node with spare."""
    import random

    universe = [
        fake.new_instance_type("medium", resources={"cpu": 4.25, "pods": 4.0})
    ]
    env = Env(universe=universe)
    env.expect_applied(make_provisioner(name="default"))
    rng = random.Random(42)
    for _ in range(10):
        batch = [make_pod(limits={"cpu": "1"}) for _ in range(rng.randint(0, 9))]
        if not batch:
            continue
        env.expect_provisioned(*batch)
        for pod in batch:
            env.expect_scheduled(pod)
        env.op.sync_state()

    nodes_with_cpu_free = 0
    for state_node in env.cluster.nodes():
        if state_node.available().get("cpu", 0.0) >= 1:
            nodes_with_cpu_free += 1
    assert nodes_with_cpu_free <= 1


def test_inflight_reuse_via_provider_ref(env):
    """suite_test.go:1799-1828 (#2011) — in-flight capacity known through a
    ProviderRef-only provisioner."""
    prov = make_provisioner(name="default")
    prov.spec.provider = None
    from karpenter_core_tpu.api.provisioner import ProviderRef

    prov.spec.provider_ref = ProviderRef(name="ref")
    env.expect_applied(prov)
    pod = make_pod(limits={"cpu": "10m"})
    env.expect_provisioned_no_binding(pod)
    assert len(env.kube.list("Node")) == 1
    env.op.sync_state()

    env.expect_applied(pod)  # still pending/unschedulable
    env.expect_provisioned_no_binding(pod)
    assert len(env.kube.list("Node")) == 1
