"""hostPort + CSI volume-limit semantics in the DEVICE solve path.

Round-2 verdict missing #1/#2: the TPU kernel co-packed same-hostPort pods
and ignored CSI attach limits on existing nodes where the reference refuses
(machine.go:69, hostportusage.go:76, existingnode.go:62-115,
volumeusage.go:33,102). These tests require the TPU and Greedy solvers to
AGREE on those refusals.
"""
import numpy as np
import pytest

from karpenter_core_tpu.api.labels import PROVISIONER_NAME_LABEL_KEY
from karpenter_core_tpu.cloudprovider import fake
from karpenter_core_tpu.kube.client import InMemoryKubeClient
from karpenter_core_tpu.kube.objects import (
    CSINode,
    CSINodeDriver,
    ObjectMeta,
    PersistentVolumeClaim,
    PersistentVolumeClaimSpec,
    PersistentVolumeClaimVolumeSource,
    StorageClass,
    Volume,
)
from karpenter_core_tpu.solver.tpu_solver import GreedySolver, TPUSolver
from karpenter_core_tpu.state.node import StateNode
from karpenter_core_tpu.testing import make_node, make_pod, make_provisioner


def run_both(pods, provisioners, its, state_nodes=None, kube_client=None,
             clone=True):
    import copy

    def sn():
        return [n.deep_copy() for n in state_nodes] if state_nodes else None

    host = GreedySolver().solve(
        copy.deepcopy(pods) if clone else pods, provisioners, its,
        state_nodes=sn(), kube_client=kube_client,
    )
    tpu = TPUSolver(max_nodes=64).solve(
        pods, provisioners, its, state_nodes=sn(), kube_client=kube_client
    )
    return host, tpu


def test_same_hostport_pods_never_colocate():
    pods = [make_pod(requests={"cpu": "0.1"}, host_ports=[8080]) for _ in range(6)]
    provisioners = [make_provisioner(name="default")]
    its = {"default": fake.instance_types(8)}
    host, tpu = run_both(pods, provisioners, its)
    assert not tpu.failed_pods and not host.failed_pods
    assert len(tpu.new_machines) == 6, "one machine per conflicting hostPort pod"
    assert len(host.new_machines) == 6
    for m in tpu.new_machines:
        assert len(m.pods) == 1


def test_distinct_hostports_share_a_node():
    pods = [
        make_pod(requests={"cpu": "0.1"}, host_ports=[8080 + i]) for i in range(4)
    ]
    provisioners = [make_provisioner(name="default")]
    its = {"default": fake.instance_types(8)}
    host, tpu = run_both(pods, provisioners, its)
    assert not tpu.failed_pods
    assert len(tpu.new_machines) == len(host.new_machines) == 1


def test_hostport_blocked_on_existing_node_with_running_pod():
    node = make_node(
        name="busy",
        labels={PROVISIONER_NAME_LABEL_KEY: "default",
                "karpenter.sh/initialized": "true"},
        capacity={"cpu": "8", "memory": "16Gi", "pods": "50"},
    )
    state = StateNode(node=node)
    running = make_pod(node_name="busy", unschedulable=False, host_ports=[443])
    state.update_for_pod(running)
    pods = [make_pod(requests={"cpu": "0.1"}, host_ports=[443])]
    provisioners = [make_provisioner(name="default")]
    its = {"default": fake.instance_types(8)}
    host, tpu = run_both(pods, provisioners, its, state_nodes=[state])
    # both solvers must refuse the existing node and open a machine
    assert not tpu.failed_pods and not host.failed_pods
    assert tpu.pod_count_existing() == 0 and host.pod_count_existing() == 0
    assert len(tpu.new_machines) == 1


def test_wildcard_ip_conflicts_with_specific_ip():
    from karpenter_core_tpu.kube.objects import ContainerPort

    p1 = make_pod(requests={"cpu": "0.1"})
    p1.spec.containers[0].ports.append(
        ContainerPort(host_port=9000, host_ip="10.0.0.1")
    )
    p2 = make_pod(requests={"cpu": "0.1"})
    p2.spec.containers[0].ports.append(ContainerPort(host_port=9000))  # 0.0.0.0
    provisioners = [make_provisioner(name="default")]
    its = {"default": fake.instance_types(8)}
    host, tpu = run_both([p1, p2], provisioners, its)
    assert len(tpu.new_machines) == len(host.new_machines) == 2


def _volume_env():
    """kube store with a StorageClass + one PVC per pod name used below."""
    client = InMemoryKubeClient()
    sc = StorageClass(metadata=ObjectMeta(name="ebs", namespace=""),
                      provisioner="ebs.csi.aws.com")
    client.create(sc)
    return client


def _pvc_pod(client, idx, requests=None):
    claim = f"data-{idx}"
    pvc = PersistentVolumeClaim(
        metadata=ObjectMeta(name=claim, namespace="default"),
        spec=PersistentVolumeClaimSpec(storage_class_name="ebs"),
    )
    client.create(pvc)
    pod = make_pod(requests=requests or {"cpu": "0.1"})
    pod.spec.volumes.append(
        Volume(name=claim,
               persistent_volume_claim=PersistentVolumeClaimVolumeSource(claim_name=claim))
    )
    return pod


def test_attach_limit_full_existing_node_skipped():
    client = _volume_env()
    node = make_node(
        name="full",
        labels={PROVISIONER_NAME_LABEL_KEY: "default",
                "karpenter.sh/initialized": "true"},
        capacity={"cpu": "8", "memory": "16Gi", "pods": "50"},
    )
    state = StateNode(node=node)
    state.volume_limits["ebs.csi.aws.com"] = 2
    # two claims already mounted: the node is at its attach limit
    state.volume_usage.volumes = {"ebs.csi.aws.com": {"default/m-0", "default/m-1"}}
    pods = [_pvc_pod(client, 0)]
    provisioners = [make_provisioner(name="default")]
    its = {"default": fake.instance_types(8)}
    host, tpu = run_both(pods, provisioners, its, state_nodes=[state],
                         kube_client=client)
    assert not tpu.failed_pods and not host.failed_pods
    assert tpu.pod_count_existing() == 0, "attach-limit-full node must be skipped"
    assert host.pod_count_existing() == 0
    assert len(tpu.new_machines) == 1


def test_attach_limit_with_headroom_accepts():
    client = _volume_env()
    node = make_node(
        name="roomy",
        labels={PROVISIONER_NAME_LABEL_KEY: "default",
                "karpenter.sh/initialized": "true"},
        capacity={"cpu": "8", "memory": "16Gi", "pods": "50"},
    )
    state = StateNode(node=node)
    state.volume_limits["ebs.csi.aws.com"] = 3
    state.volume_usage.volumes = {"ebs.csi.aws.com": {"default/m-0"}}
    pods = [_pvc_pod(client, i) for i in range(4)]  # 2 fit (limit 3, 1 used)
    provisioners = [make_provisioner(name="default")]
    its = {"default": fake.instance_types(8)}
    host, tpu = run_both(pods, provisioners, its, state_nodes=[state],
                         kube_client=client)
    assert not tpu.failed_pods
    assert tpu.pod_count_existing() == host.pod_count_existing() == 2
    assert tpu.pod_count_new() == 2


def test_shared_claim_counts_once():
    """Two pods mounting the SAME claim count one attachment (dedup by
    volume id, volumeusage.go:44-56)."""
    client = _volume_env()
    pvc = PersistentVolumeClaim(
        metadata=ObjectMeta(name="shared", namespace="default"),
        spec=PersistentVolumeClaimSpec(storage_class_name="ebs"),
    )
    client.create(pvc)

    def shared_pod():
        pod = make_pod(requests={"cpu": "0.1"})
        pod.spec.volumes.append(
            Volume(name="shared",
                   persistent_volume_claim=PersistentVolumeClaimVolumeSource(
                       claim_name="shared")))
        return pod

    node = make_node(
        name="one-slot",
        labels={PROVISIONER_NAME_LABEL_KEY: "default",
                "karpenter.sh/initialized": "true"},
        capacity={"cpu": "8", "memory": "16Gi", "pods": "50"},
    )
    state = StateNode(node=node)
    state.volume_limits["ebs.csi.aws.com"] = 1
    pods = [shared_pod(), shared_pod()]
    provisioners = [make_provisioner(name="default")]
    its = {"default": fake.instance_types(8)}
    host, tpu = run_both(pods, provisioners, its, state_nodes=[state],
                         kube_client=client)
    assert not tpu.failed_pods
    # both pods share one attachment: both fit on the limit-1 node
    assert tpu.pod_count_existing() == host.pod_count_existing() == 2


def test_volume_limits_resolve_from_csinode_without_cluster():
    """CSI attach limits must bind even when state_nodes bypass the
    cluster informer (the gRPC boundary / direct API shape): both solver
    paths resolve them from the CSINode objects (state/node.py
    resolve_volume_limits; reference cluster.go:430-444 +
    existingnode.go:62-115). Regression: found by the deep fuzz sweep —
    an existing node took 4 distinct claims against a limit of 3."""
    from karpenter_core_tpu.api.labels import (
        LABEL_CAPACITY_TYPE,
        LABEL_NODE_INITIALIZED,
        PROVISIONER_NAME_LABEL_KEY,
    )
    from karpenter_core_tpu.kube.client import InMemoryKubeClient
    from karpenter_core_tpu.kube.objects import (
        CSINode,
        CSINodeDriver,
        LABEL_INSTANCE_TYPE_STABLE,
        LABEL_TOPOLOGY_ZONE,
        ObjectMeta,
        PersistentVolumeClaim,
        PersistentVolumeClaimSpec,
        PersistentVolumeClaimVolumeSource,
        StorageClass,
        Volume,
    )
    from karpenter_core_tpu.solver.tpu_solver import GreedySolver, TPUSolver
    from karpenter_core_tpu.state.node import StateNode
    from karpenter_core_tpu.testing import make_node

    kube = InMemoryKubeClient()
    kube.create(StorageClass(metadata=ObjectMeta(name="sc", namespace=""),
                             provisioner="x.csi"))
    pods = []
    for i in range(5):
        name = f"c{i}"
        kube.create(PersistentVolumeClaim(
            metadata=ObjectMeta(name=name, namespace="default"),
            spec=PersistentVolumeClaimSpec(storage_class_name="sc")))
        p = make_pod(requests={"cpu": "1"})
        p.spec.volumes.append(Volume(
            name=name,
            persistent_volume_claim=PersistentVolumeClaimVolumeSource(
                claim_name=name)))
        pods.append(p)
    universe = fake.instance_types(12)
    it = universe[8]  # 9-cpu type: capacity would admit all 5
    node = make_node(
        name="e0",
        labels={
            PROVISIONER_NAME_LABEL_KEY: "default",
            LABEL_NODE_INITIALIZED: "true",
            LABEL_INSTANCE_TYPE_STABLE: it.name,
            LABEL_CAPACITY_TYPE: "on-demand",
            LABEL_TOPOLOGY_ZONE: "test-zone-1",
        },
        capacity={k: str(v) for k, v in it.capacity.items()},
    )
    nodes = [StateNode(node=node)]
    kube.create(CSINode(metadata=ObjectMeta(name="e0"),
                        drivers=[CSINodeDriver(name="x.csi",
                                               allocatable_count=3)]))
    provs = [make_provisioner(name="default")]
    for solver in (TPUSolver(max_nodes=8), GreedySolver()):
        res = solver.solve(
            pods, provs, {"default": universe},
            state_nodes=[n.deep_copy() for n in nodes], kube_client=kube,
        )
        assert not res.failed_pods
        for _n, ps in res.existing_assignments:
            assert len(ps) == 3, "CSI limit must cap the existing node at 3"
        assert sum(len(m.pods) for m in res.new_machines) == 2
