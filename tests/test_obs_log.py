"""Structured-logging suite (ISSUE 3 tentpole): level gating, logfmt/JSON
formatting, per-thread bound context, trace-id correlation with the
tracer, the bounded ring, env parsing, and the disabled fast path."""
import io
import json
import threading

import pytest

from karpenter_core_tpu.obs.log import (
    DEBUG,
    ERROR,
    INFO,
    OFF,
    WARNING,
    LogSink,
    bound,
    bound_context,
    configure_logging_from_env,
    format_json,
    format_logfmt,
    get_logger,
    parse_log_spec,
)


@pytest.fixture
def sink(monkeypatch):
    """A fresh sink wired in as the module singleton, with a capture
    stream."""
    import karpenter_core_tpu.obs.log as log_mod

    fresh = LogSink(capacity=64)
    fresh.configure(level=INFO, fmt="logfmt", stream=io.StringIO())
    monkeypatch.setattr(log_mod, "SINK", fresh)
    return fresh


# -- level gating ------------------------------------------------------------


def test_level_gating(sink):
    log = get_logger("karpenter.test")
    log.debug("dropped")
    log.info("kept")
    log.warning("kept too")
    assert [r["msg"] for r in sink.records()] == ["kept", "kept too"]
    sink.level = ERROR
    log.warning("now dropped")
    log.error("boom")
    assert [r["msg"] for r in sink.records()][-1] == "boom"
    assert [r["level"] for r in sink.records()] == ["info", "warning", "error"]


def test_disabled_path_no_records(sink):
    sink.disable()
    log = get_logger("karpenter.test")
    log.info("nope", big_field="x" * 1000)
    log.debug("nope")
    log.warning("nope")
    assert sink.records() == []
    assert sink.stream.getvalue() == ""
    assert not sink.enabled and sink.level == OFF


def test_errors_bypass_disabled_sink(sink, capsys):
    """Last-resort semantics (stdlib lastResort analog): error records from
    a process that never configured the sink still ring and reach stderr —
    a crashing watch pump must never be invisible."""
    sink.disable()
    log = get_logger("karpenter.test")
    log.error("still visible", kind="Pod")
    assert sink.records()[-1]["msg"] == "still visible"
    assert "still visible" in sink.stream.getvalue()  # configured stream wins
    # with NO stream configured at all, stderr is the last resort
    sink.stream = None
    try:
        raise RuntimeError("pump died")
    except RuntimeError:
        log.exception("watch pump failed")
    assert "watch pump failed" in capsys.readouterr().err
    assert sink.records()[-1]["error"] == "RuntimeError"


# -- bound context -----------------------------------------------------------


def test_bound_context_nests_and_unwinds(sink):
    log = get_logger("karpenter.test")
    with bound(controller="provisioning", reconcile="r7"):
        log.info("outer")
        with bound(phase="launch"):
            log.info("inner")
            assert bound_context() == {
                "controller": "provisioning", "reconcile": "r7",
                "phase": "launch",
            }
        log.info("outer again")
    log.info("unbound")
    records = sink.records()
    assert records[0]["controller"] == "provisioning"
    assert "phase" not in records[0]
    assert records[1]["phase"] == "launch"
    assert records[1]["reconcile"] == "r7"  # inherited from the outer scope
    assert "phase" not in records[2]
    assert "controller" not in records[3]


def test_bound_context_is_per_thread(sink):
    log = get_logger("karpenter.test")
    seen = {}

    def worker():
        seen["ctx"] = bound_context()
        log.info("from thread")

    with bound(controller="provisioning"):
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    assert seen["ctx"] == {}  # other threads never see this thread's binding
    thread_rec = next(r for r in sink.records() if r["msg"] == "from thread")
    assert "controller" not in thread_rec


def test_call_fields_override_bound(sink):
    log = get_logger("karpenter.test")
    with bound(controller="a"):
        log.info("x", controller="b")
    assert sink.records()[0]["controller"] == "b"


# -- trace correlation -------------------------------------------------------


def test_trace_id_correlation(sink):
    from karpenter_core_tpu.obs.tracer import Tracer

    import karpenter_core_tpu.obs.log as log_mod

    tracer = Tracer(capacity=16)
    tracer.enable()
    orig = log_mod.TRACER
    log_mod.TRACER = tracer
    try:
        log = get_logger("karpenter.test")
        log.info("outside any span")
        with tracer.span("solver.solve") as sp:
            log.info("inside the solve")
            trace_id = sp.trace_id
    finally:
        log_mod.TRACER = orig
    records = sink.records()
    assert "trace_id" not in records[0]
    assert records[1]["trace_id"] == trace_id  # log line joins the span


# -- exception capture -------------------------------------------------------


def test_exception_fields(sink):
    log = get_logger("karpenter.test")
    try:
        raise ValueError("bad geometry")
    except ValueError:
        log.exception("solve failed", pods=3)
    (record,) = sink.records()
    assert record["error"] == "ValueError"
    assert record["error_detail"] == "bad geometry"
    assert "ValueError: bad geometry" in record["stack"]
    assert record["pods"] == 3


# -- formatting --------------------------------------------------------------


def test_logfmt_escaping():
    line = format_logfmt(
        {
            "ts": 1700000000.5,
            "level": "info",
            "logger": "karpenter.x",
            "msg": 'has spaces and "quotes"',
            "count": 3,
            "ratio": 0.25,
            "ok": True,
            "plain": "word",
        }
    )
    assert 'msg="has spaces and \\"quotes\\""' in line
    assert "count=3" in line and "ratio=0.25" in line
    assert "ok=true" in line and "plain=word" in line
    assert line.startswith("ts=2023-11-14T")


def test_json_format_round_trips():
    record = {
        "ts": 1700000000.0, "level": "warning", "logger": "karpenter.x",
        "msg": "m", "nested": "a=b c", "n": 7,
    }
    parsed = json.loads(format_json(record))
    assert parsed["level"] == "warning"
    assert parsed["n"] == 7
    assert parsed["ts"].endswith("Z")


# -- ring --------------------------------------------------------------------


def test_ring_bounded_with_drop_accounting(sink):
    log = get_logger("karpenter.test")
    for i in range(100):
        log.info(f"m{i}")
    assert len(sink.records()) == 64
    assert sink.dropped == 36
    assert sink.records()[0]["msg"] == "m36"  # newest kept
    assert "# dropped=36" in sink.lines()
    sink.clear()
    assert sink.records() == [] and sink.dropped == 0


def test_lines_formats(sink):
    get_logger("karpenter.test").info("hello", k="v")
    assert "msg=hello" in sink.lines()
    assert json.loads(sink.lines(fmt="json").splitlines()[0])["k"] == "v"


def test_dead_stream_never_raises(sink):
    class Dead:
        def write(self, s):
            raise OSError("broken pipe")

    sink.stream = Dead()
    get_logger("karpenter.test").info("still records")
    assert sink.records()[-1]["msg"] == "still records"


# -- env parsing -------------------------------------------------------------


def test_parse_log_spec():
    assert parse_log_spec("") is None
    assert parse_log_spec("off") is None
    assert parse_log_spec("0") is None
    assert parse_log_spec("1") == (INFO, "logfmt")
    assert parse_log_spec("true") == (INFO, "logfmt")
    assert parse_log_spec("debug") == (DEBUG, "logfmt")
    assert parse_log_spec("warn") == (WARNING, "logfmt")
    assert parse_log_spec("error:json") == (ERROR, "json")
    assert parse_log_spec("json") == (INFO, "json")
    assert parse_log_spec("DEBUG:JSON".lower()) == (DEBUG, "json")
    # a typo'd level still logs (info) instead of silently disabling
    assert parse_log_spec("verbose") == (INFO, "logfmt")


def test_configure_from_env(monkeypatch):
    import karpenter_core_tpu.obs.log as log_mod

    was_level, was_fmt, was_stream = (
        log_mod.SINK.level, log_mod.SINK.fmt, log_mod.SINK.stream
    )
    try:
        monkeypatch.setenv("KARPENTER_TPU_LOG", "debug:json")
        assert configure_logging_from_env() is True
        assert log_mod.SINK.level == DEBUG and log_mod.SINK.fmt == "json"
        monkeypatch.setenv("KARPENTER_TPU_LOG", "off")
        # an explicit off wins over the entrypoint default
        assert configure_logging_from_env(default_level="info") is False
        monkeypatch.setenv("KARPENTER_TPU_LOG", "")
        assert configure_logging_from_env(default_level="info") is True
        assert log_mod.SINK.level == INFO
        assert configure_logging_from_env() is False  # unset + no default
    finally:
        log_mod.SINK.level, log_mod.SINK.fmt = was_level, was_fmt
        log_mod.SINK.stream = was_stream


# -- integration: the operator loop binds controller/reconcile ---------------


def test_singleton_reconcile_binds_context(sink):
    from karpenter_core_tpu.operator.controller import Singleton

    captured = {}

    def reconcile():
        captured.update(bound_context())
        get_logger("karpenter.test").info("inside reconcile")
        return None

    Singleton("unit-test", reconcile).reconcile_once()
    assert captured["controller"] == "unit-test"
    assert captured["reconcile"].startswith("r")
    record = next(r for r in sink.records() if r["msg"] == "inside reconcile")
    assert record["controller"] == "unit-test"
    assert record["reconcile"] == captured["reconcile"]


def test_reconcile_failure_logs_structured(sink):
    from karpenter_core_tpu.operator.controller import Singleton

    def reconcile():
        raise RuntimeError("injected")

    s = Singleton("failing", reconcile)
    backoff = s.reconcile_once()
    assert backoff is not None and backoff > 0
    record = next(r for r in sink.records() if r["msg"] == "reconcile failed")
    assert record["controller"] == "failing"
    assert record["failures"] == 1
    assert record["error"] == "RuntimeError"
    # the failure line carries the pass's reconcile id even though the
    # bound scope has unwound — a failing pass greps as one unit
    assert record["reconcile"].startswith("r")
