"""Spec-for-spec port of the Requirement algebra suite.

Reference pkg/scheduling/requirement_test.go: the full 14x14 pairwise
Intersection table (requirement_test.go:82-292), the Has value table
(:295-370), Operator recovery (:373-388), complement-set Len (:391-406),
Any (:409-424), String (:427-444), and NodeSelectorRequirement conversion
(:447-462) — every expectation transcribed, not recomputed, so the table
is an independent oracle for the host algebra (which the device encoder
mirrors; ops/compat.py carries the tensor twin).

The 14 fixtures mirror requirement_test.go:29-42. `CB` builds the
compound complement results the reference spells as raw struct literals
(complement sets carrying Gt/Lt bounds, requirement_test.go:167,223,
228-231,242,246,287-288).
"""
import pytest

from karpenter_core_tpu.scheduling.requirement import MAX_LEN, Requirement


def R(op, *values):
    return Requirement("key", op, list(values))


def CB(values=(), gt=None, lt=None):
    """Complement set with optional integer bounds (the reference's
    &Requirement{complement: true, ...} literals)."""
    return Requirement._make("key", True, set(values), gt, lt)


exists = R("Exists")
dne = R("DoesNotExist")
inA = R("In", "A")
inB = R("In", "B")
inAB = R("In", "A", "B")
notInA = R("NotIn", "A")
in1 = R("In", "1")
in9 = R("In", "9")
in19 = R("In", "1", "9")
notIn12 = R("NotIn", "1", "2")
gt1 = R("Gt", "1")
gt9 = R("Gt", "9")
lt1 = R("Lt", "1")
lt9 = R("Lt", "9")

FIXTURES = [
    ("exists", exists), ("dne", dne), ("inA", inA), ("inB", inB),
    ("inAB", inAB), ("notInA", notInA), ("in1", in1), ("in9", in9),
    ("in19", in19), ("notIn12", notIn12), ("gt1", gt1), ("gt9", gt9),
    ("lt1", lt1), ("lt9", lt9),
]

# the complete Intersection table, rows/cols in FIXTURES order, each cell
# transcribed from requirement_test.go:83-291
INTERSECTION_TABLE = {
    "exists": [exists, dne, inA, inB, inAB, notInA, in1, in9, in19,
               notIn12, gt1, gt9, lt1, lt9],
    "dne": [dne] * 14,
    "inA": [inA, dne, inA, dne, inA, dne, dne, dne, dne, inA,
            dne, dne, dne, dne],
    "inB": [inB, dne, dne, inB, inB, inB, dne, dne, dne, inB,
            dne, dne, dne, dne],
    "inAB": [inAB, dne, inA, inB, inAB, inB, dne, dne, dne, inAB,
             dne, dne, dne, dne],
    "notInA": [notInA, dne, dne, inB, inB, notInA, in1, in9, in19,
               CB({"A", "1", "2"}), gt1, gt9, lt1, lt9],
    "in1": [in1, dne, dne, dne, dne, in1, in1, dne, in1, dne,
            dne, dne, dne, in1],
    "in9": [in9, dne, dne, dne, dne, in9, dne, in9, in9, in9,
            in9, dne, dne, dne],
    "in19": [in19, dne, dne, dne, dne, in19, in1, in9, in19, in9,
             in9, dne, dne, in1],
    "notIn12": [notIn12, dne, inA, inB, inAB, CB({"A", "1", "2"}),
                dne, in9, in9, notIn12, CB({"2"}, gt=1), CB(gt=9),
                CB(lt=1), CB({"1", "2"}, lt=9)],
    "gt1": [gt1, dne, dne, dne, dne, gt1, dne, in9, in9,
            CB({"2"}, gt=1), gt1, gt9, dne, CB(gt=1, lt=9)],
    "gt9": [gt9, dne, dne, dne, dne, gt9, dne, dne, dne, gt9,
            gt9, gt9, dne, dne],
    "lt1": [lt1, dne, dne, dne, dne, lt1, dne, dne, dne, lt1,
            dne, dne, lt1, lt1],
    "lt9": [lt9, dne, dne, dne, dne, lt9, in1, dne, in1,
            CB({"1", "2"}, lt=9), CB(gt=1, lt=9), dne, lt1, lt9],
}


def test_normalize_labels_across_construction_paths():
    """requirement_test.go:45-79 — the 5 beta-label aliases normalize to
    the stable keys through every Requirements construction path: label
    map, NodeSelectorRequirement list, and the pod path (nodeSelector +
    required + preferred node affinity)."""
    from karpenter_core_tpu.kube.objects import (
        LABEL_ARCH_STABLE,
        LABEL_INSTANCE_TYPE_STABLE,
        LABEL_OS_STABLE,
        LABEL_TOPOLOGY_REGION,
        LABEL_TOPOLOGY_ZONE,
        NodeSelectorRequirement,
        NodeSelectorTerm,
        PreferredSchedulingTerm,
    )
    from karpenter_core_tpu.scheduling.requirements import Requirements
    from karpenter_core_tpu.testing import make_pod

    node_selector = {
        "failure-domain.beta.kubernetes.io/zone": "test",
        "failure-domain.beta.kubernetes.io/region": "test",
        "beta.kubernetes.io/arch": "test",
        "beta.kubernetes.io/os": "test",
        "beta.kubernetes.io/instance-type": "test",
    }
    reqs = [
        NodeSelectorRequirement(key=k, operator="In", values=[v])
        for k, v in node_selector.items()
    ]
    want = {
        LABEL_ARCH_STABLE,
        LABEL_OS_STABLE,
        LABEL_INSTANCE_TYPE_STABLE,
        LABEL_TOPOLOGY_REGION,
        LABEL_TOPOLOGY_ZONE,
    }
    pod = make_pod(
        node_selector=dict(node_selector),
        node_affinity_required=[NodeSelectorTerm(match_expressions=reqs)],
        node_affinity_preferred=[
            PreferredSchedulingTerm(
                weight=1, preference=NodeSelectorTerm(match_expressions=reqs)
            )
        ],
    )
    for r in [
        Requirements.from_labels(dict(node_selector)),
        Requirements.from_node_selector_requirements(*reqs),
        Requirements.from_pod(pod),
    ]:
        assert r.keys_set() == want, sorted(r.keys_set())


@pytest.mark.parametrize("row", [name for name, _ in FIXTURES])
def test_intersection_table(row):
    """requirement_test.go:82-292 — the full pairwise table."""
    left = dict(FIXTURES)[row]
    for (col, right), want in zip(FIXTURES, INTERSECTION_TABLE[row]):
        got = left.intersection(right)
        assert got == want, f"{row} ∩ {col}: got {got!r}, want {want!r}"


# Has table (requirement_test.go:295-370): per probed value, the expected
# result per fixture in FIXTURES order
HAS_TABLE = {
    "A": [True, False, True, False, True, False, False, False, False,
          True, False, False, False, False],
    "B": [True, False, False, True, True, True, False, False, False,
          True, False, False, False, False],
    "1": [True, False, False, False, False, True, True, False, True,
          False, False, False, False, True],
    "2": [True, False, False, False, False, True, False, False, False,
          False, True, False, False, True],
    "9": [True, False, False, False, False, True, False, True, True,
          True, True, False, False, False],
}


@pytest.mark.parametrize("value", sorted(HAS_TABLE))
def test_has_table(value):
    """requirement_test.go:295-370"""
    for (name, req), want in zip(FIXTURES, HAS_TABLE[value]):
        assert req.has(value) is want, f"{name}.has({value!r})"


def test_operator_recovery():
    """requirement_test.go:373-388 — Gt/Lt recover as Exists."""
    want = ["Exists", "DoesNotExist", "In", "In", "In", "NotIn", "In",
            "In", "In", "NotIn", "Exists", "Exists", "Exists", "Exists"]
    for (name, req), op in zip(FIXTURES, want):
        assert req.operator() == op, name


def test_len_complement_counting():
    """requirement_test.go:391-406 — complement sets count down from the
    max-int universe."""
    want = [MAX_LEN, 0, 1, 1, 2, MAX_LEN - 1, 1, 1, 2, MAX_LEN - 2,
            MAX_LEN, MAX_LEN, MAX_LEN, MAX_LEN]
    for (name, req), n in zip(FIXTURES, want):
        assert req.len() == n, name


def test_any():
    """requirement_test.go:409-424"""
    assert exists.any() != ""
    assert dne.any() == ""
    assert inA.any() == "A"
    assert inB.any() == "B"
    assert inAB.any() in ("A", "B")
    assert notInA.any() not in ("", "A")
    assert in1.any() == "1"
    assert in9.any() == "9"
    assert in19.any() in ("1", "9")
    assert notIn12.any() not in ("", "1", "2")
    assert int(gt1.any()) >= 1
    assert 9 <= int(gt9.any()) < MAX_LEN
    assert lt1.any() == "0"
    assert 0 <= int(lt9.any()) < 9


def test_string():
    """requirement_test.go:427-444 — same cases, the repo's repr format
    (python list syntax instead of Go's space-joined values)."""
    assert repr(exists) == "key Exists"
    assert repr(dne) == "key DoesNotExist"
    assert repr(inA) == "key In ['A']"
    assert repr(inB) == "key In ['B']"
    assert repr(inAB) == "key In ['A', 'B']"
    assert repr(notInA) == "key NotIn ['A']"
    assert repr(in1) == "key In ['1']"
    assert repr(in9) == "key In ['9']"
    assert repr(in19) == "key In ['1', '9']"
    assert repr(notIn12) == "key NotIn ['1', '2']"
    assert repr(gt1) == "key Exists >1"
    assert repr(gt9) == "key Exists >9"
    assert repr(lt1) == "key Exists <1"
    assert repr(lt9) == "key Exists <9"
    assert repr(gt1.intersection(lt9)) == "key Exists >1 <9"
    # an empty integer interval collapses to DoesNotExist
    assert repr(gt9.intersection(lt1)) == "key DoesNotExist"


def test_node_selector_requirement_conversion():
    """requirement_test.go:447-462"""
    cases = [
        (exists, "Exists", []),
        (dne, "DoesNotExist", []),
        (inA, "In", ["A"]),
        (inB, "In", ["B"]),
        (inAB, "In", ["A", "B"]),
        (notInA, "NotIn", ["A"]),
        (in1, "In", ["1"]),
        (in9, "In", ["9"]),
        (in19, "In", ["1", "9"]),
        (notIn12, "NotIn", ["1", "2"]),
        (gt1, "Gt", ["1"]),
        (gt9, "Gt", ["9"]),
        (lt1, "Lt", ["1"]),
        (lt9, "Lt", ["9"]),
    ]
    for req, op, values in cases:
        nsr = req.to_node_selector_requirement()
        assert nsr.key == "key"
        assert nsr.operator == op
        assert sorted(nsr.values or []) == values
