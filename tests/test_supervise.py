"""Wedge-proof execution supervisor (ISSUE 11): heartbeat staleness vs
slow-but-alive, atomic resumable artifacts, restart backoff, process-group
kill semantics (incl. surviving grandchildren), env redaction, and TTL'd
health verdicts. Everything here is subprocess-real but jax-free — the
supervisor's whole job is to work when the accelerator stack doesn't."""
import json
import os
import signal
import sys
import textwrap
import time

from karpenter_core_tpu.utils import supervise


def _script(body: str) -> list:
    return [sys.executable, "-c", textwrap.dedent(body)]


# ---------------------------------------------------------------------------
# heartbeats: wedge (stale) is DISTINCT from slow (alive but over budget)


def test_slow_but_alive_worker_times_out_not_wedged(tmp_path):
    """A worker still touching its heartbeat past the budget is SLOW: the
    supervisor kills it at the budget with timed_out=True, wedged=False."""
    hb = str(tmp_path / "hb")
    res = supervise.run_supervised(
        _script(f"""
            import os, time
            for _ in range(200):
                with open({hb!r}, "a"):
                    os.utime({hb!r}, None)
                time.sleep(0.1)
        """),
        timeout_s=2.0, heartbeat_path=hb, stale_after_s=1.0, poll_s=0.1,
    )
    assert not res.ok
    assert res.timed_out and not res.wedged
    assert "slow, not wedged" in res.note


def test_stale_heartbeat_is_a_wedge_and_kills_early(tmp_path):
    """A worker that STOPS touching is wedged: killed at the staleness
    threshold, long before the wall budget burns down."""
    hb = str(tmp_path / "hb")
    start = time.monotonic()
    res = supervise.run_supervised(
        _script(f"""
            import os, time
            with open({hb!r}, "a"):
                os.utime({hb!r}, None)
            time.sleep(60)  # the wedge: silence
        """),
        timeout_s=30.0, heartbeat_path=hb, stale_after_s=1.0, poll_s=0.1,
    )
    took = time.monotonic() - start
    assert res.wedged and not res.timed_out
    assert "wedged" in res.note
    assert took < 15, f"wedge must be detected early, took {took:.1f}s"


def test_never_touched_heartbeat_counts_as_wedge(tmp_path):
    """A worker that never touches at all is indistinguishable from one
    that wedged during startup: same early kill."""
    hb = str(tmp_path / "hb")
    res = supervise.run_supervised(
        _script("import time; time.sleep(60)"),
        timeout_s=30.0, heartbeat_path=hb, stale_after_s=1.0, poll_s=0.1,
    )
    assert res.wedged


def test_wedge_log_carries_redacted_output_tails(tmp_path):
    """The post-mortem payload: last bytes of both streams, env-redacted."""
    hb = str(tmp_path / "hb")
    env = dict(os.environ)
    env["KCT_TEST_SECRET_TOKEN"] = "hunter2hunter2"
    res = supervise.run_supervised(
        _script("""
            import sys, time
            print("progress line on stdout")
            print("tunnel auth hunter2hunter2 then silence", file=sys.stderr)
            sys.stdout.flush(); sys.stderr.flush()
            time.sleep(60)
        """),
        env=env, timeout_s=30.0, heartbeat_path=hb, stale_after_s=1.0,
        poll_s=0.1,
    )
    log = res.wedge_log()
    assert log["wedged"] is True
    assert "progress line on stdout" in log["stdout_tail"]
    assert "then silence" in log["stderr_tail"]
    assert "hunter2hunter2" not in log["stderr_tail"]
    assert "<redacted:KCT_TEST_SECRET_TOKEN>" in log["stderr_tail"]


def test_redact_env_text_only_sensitive_names():
    env = {"MY_API_KEY": "supersecretvalue", "HOME": "/root", "X": "ab"}
    out = supervise.redact_env_text(
        "key=supersecretvalue home=/root x=ab", environ=env
    )
    assert "supersecretvalue" not in out
    assert "<redacted:MY_API_KEY>" in out
    assert "/root" in out  # non-sensitive name untouched


# ---------------------------------------------------------------------------
# process-group kill: grandchildren die with the worker


def test_kill_reaps_the_whole_process_group(tmp_path):
    """A worker that forked helpers (the fork-bomb shape: grandchildren
    that would survive a plain child kill) loses its WHOLE group on
    wedge — no orphan keeps a pipe or a device handle alive."""
    pid_file = str(tmp_path / "pids")
    hb = str(tmp_path / "hb")
    res = supervise.run_supervised(
        _script(f"""
            import os, subprocess, sys, time
            procs = [
                subprocess.Popen([sys.executable, "-c", "import time; time.sleep(120)"])
                for _ in range(3)
            ]
            with open({pid_file!r}, "w") as f:
                f.write(" ".join(str(p.pid) for p in procs))
            time.sleep(120)  # wedge with the grandchildren running
        """),
        timeout_s=60.0, heartbeat_path=hb, stale_after_s=1.5, poll_s=0.1,
    )
    assert res.wedged
    with open(pid_file) as f:
        pids = [int(p) for p in f.read().split()]
    assert len(pids) == 3
    # SIGKILL is asynchronous; give the kernel a moment to reap
    deadline = time.monotonic() + 10
    alive = pids
    while alive and time.monotonic() < deadline:
        alive = [p for p in alive if _alive(p)]
        time.sleep(0.1)
    assert not alive, f"grandchildren survived the group kill: {alive}"


def _alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    # zombies are "alive" to kill(0); check the state instead
    try:
        with open(f"/proc/{pid}/stat") as f:
            return f.read().split()[2] != "Z"
    except OSError:
        return False


# ---------------------------------------------------------------------------
# restart with bounded backoff


def test_restart_backoff_until_success(tmp_path):
    """rc!=0 attempts restart with doubling backoff; the first clean exit
    stops the loop. The counter file makes attempt 3 succeed."""
    counter = str(tmp_path / "count")
    sleeps = []
    res = supervise.run_supervised(
        _script(f"""
            import os, sys
            n = int(open({counter!r}).read()) if os.path.exists({counter!r}) else 0
            open({counter!r}, "w").write(str(n + 1))
            sys.exit(0 if n >= 2 else 1)
        """),
        timeout_s=30.0, max_restarts=5, backoff_base_s=0.05,
        backoff_max_s=0.2, poll_s=0.05, sleep=sleeps.append,
    )
    assert res.ok and res.rc == 0
    assert res.restarts == 2
    assert sleeps == [0.05, 0.1], "doubling backoff between failed attempts"
    assert len(res.attempts) == 3 and res.attempts[-1] == "attempt 3: rc=0"


def test_restart_budget_is_bounded(tmp_path):
    res = supervise.run_supervised(
        _script("import sys; sys.exit(3)"),
        timeout_s=30.0, max_restarts=2, backoff_base_s=0.01, poll_s=0.05,
        sleep=lambda s: None,
    )
    assert not res.ok
    assert res.rc == 3
    assert res.restarts == 2 and len(res.attempts) == 3


# ---------------------------------------------------------------------------
# atomic resumable artifacts


def test_artifact_roundtrip_and_digest_gating(tmp_path):
    store = supervise.ArtifactStore(str(tmp_path / "stages"))
    cfg = {"stage": "headline", "pods": 200}
    store.save("headline", cfg, {"e2e_p99_ms": 410.0})
    rec = store.fresh("headline", cfg)
    assert rec is not None and rec["data"]["e2e_p99_ms"] == 410.0
    # a changed config invalidates the artifact (content-keyed resume)
    assert store.fresh("headline", {"stage": "headline", "pods": 500}) is None
    # degraded artifacts are never fresh — a resume re-runs them
    store.save("headline", cfg, None, degraded=True, error="wedged",
               wedge_log={"note": "killed"})
    assert store.fresh("headline", cfg) is None
    loaded = store.load("headline")
    assert loaded["degraded"] and loaded["wedge_log"]["note"] == "killed"


def test_artifact_write_is_atomic(tmp_path):
    """No partial file is ever visible: the write is temp + rename in the
    same directory, and a failed dump leaves the previous version."""
    store = supervise.ArtifactStore(str(tmp_path / "stages"))
    cfg = {"stage": "s"}
    store.save("s", cfg, {"v": 1})
    try:
        supervise.atomic_write_json(
            store.path("s"), {"bad": object()}  # not JSON-serializable
        )
    except TypeError:
        pass
    rec = store.load("s")
    assert rec is not None and rec["data"]["v"] == 1, "old version preserved"
    leftovers = [n for n in os.listdir(store.root) if n.startswith(".tmp-")]
    assert not leftovers, f"temp files leaked: {leftovers}"


def test_artifact_corrupt_file_reads_as_missing(tmp_path):
    store = supervise.ArtifactStore(str(tmp_path / "stages"))
    with open(store.path("x"), "w") as f:
        f.write("{not json")
    assert store.load("x") is None
    assert store.fresh("x", {"stage": "x"}) is None


def test_fallback_artifacts_are_fresh_but_flagged(tmp_path):
    """An involuntary-CPU column is COMPLETE (fresh) but flagged: the
    bench planner re-runs it only when the TPU verdict comes back."""
    store = supervise.ArtifactStore(str(tmp_path / "stages"))
    cfg = {"stage": "headline"}
    store.save("headline", cfg, {"pods_per_sec": 1.0}, fallback=True)
    rec = store.fresh("headline", cfg)
    assert rec is not None and rec["fallback"] is True


# ---------------------------------------------------------------------------
# TTL'd health verdicts


def test_verdict_roundtrip_and_ttl(tmp_path):
    path = str(tmp_path / "health.json")
    supervise.write_verdict(path, True, "tpu v5e", ttl_s=60.0)
    v = supervise.read_verdict(path)
    assert v is not None and v["ok"] and v["note"] == "tpu v5e"
    supervise.write_verdict(path, True, "soon stale", ttl_s=0.05)
    time.sleep(0.1)
    assert supervise.read_verdict(path) is None, "stale verdict = no verdict"


def test_verdict_missing_or_corrupt_is_none(tmp_path):
    assert supervise.read_verdict(str(tmp_path / "nope.json")) is None
    bad = str(tmp_path / "bad.json")
    with open(bad, "w") as f:
        f.write("[]")
    assert supervise.read_verdict(bad) is None
    with open(bad, "w") as f:
        json.dump({"ok": True}, f)  # no ts/ttl
    assert supervise.read_verdict(bad) is None


# ---------------------------------------------------------------------------
# in-process thread heartbeats (the ResilientSolver watchdog's view)


def test_thread_heartbeat_age_and_thread_local_binding():
    clock = {"t": 100.0}
    hb = supervise.ThreadHeartbeat(clock=lambda: clock["t"])
    assert hb.age() is None, "never touched"
    hb.touch()
    clock["t"] += 2.5
    assert hb.age() == 2.5
    # the thread-local hook: unbound is a no-op, bound touches
    supervise.bind_heartbeat(None)
    supervise.touch_heartbeat()  # must not raise
    supervise.bind_heartbeat(hb)
    try:
        supervise.touch_heartbeat()
        assert hb.age() == 0.0
        assert supervise.bound_heartbeat() is hb
    finally:
        supervise.bind_heartbeat(None)


def test_salvaged_stdout_survives_a_wedge_kill(tmp_path):
    """A worker that printed its result line and THEN wedged still hands
    the supervisor the line (the bench salvages such stages)."""
    hb = str(tmp_path / "hb")
    res = supervise.run_supervised(
        _script("""
            import sys, time
            print('{"stage": "x", "data": {"v": 7}}')
            sys.stdout.flush()
            time.sleep(60)
        """),
        timeout_s=30.0, heartbeat_path=hb, stale_after_s=1.0, poll_s=0.1,
    )
    assert res.wedged
    assert json.loads(res.stdout.strip())["data"]["v"] == 7


def test_sigkill_is_used_not_sigterm(tmp_path):
    """The kill must be UNCATCHABLE: a worker shielding itself with a
    SIGTERM handler dies anyway (the axon wedge does not cooperate)."""
    hb = str(tmp_path / "hb")
    res = supervise.run_supervised(
        _script("""
            import signal, time
            signal.signal(signal.SIGTERM, lambda *a: None)
            time.sleep(60)
        """),
        timeout_s=30.0, heartbeat_path=hb, stale_after_s=1.0, poll_s=0.1,
    )
    assert res.wedged
    assert res.rc in (-signal.SIGKILL, None)


# ---------------------------------------------------------------------------
# phase-labeled heartbeats (ISSUE 15): a wedge names WHERE the worker died


def test_heartbeat_label_roundtrip(tmp_path):
    hb = supervise.Heartbeat(str(tmp_path / "hb"))
    assert hb.read_label() == ""
    hb.touch("solver.phase.device")
    assert hb.read_label() == "solver.phase.device"
    # a label-less progress tick preserves the last label
    hb.touch()
    assert hb.read_label() == "solver.phase.device"
    hb.touch("solver.phase.fetch")
    assert hb.read_label() == "solver.phase.fetch"


def test_thread_heartbeat_label(tmp_path):
    hb = supervise.ThreadHeartbeat()
    assert hb.label() == ""
    hb.touch("solver.phase.prescreen")
    assert hb.label() == "solver.phase.prescreen"
    hb.touch()  # tick keeps the label
    assert hb.label() == "solver.phase.prescreen"


def test_touch_heartbeat_hook_labels_both_layers(tmp_path):
    thread_hb = supervise.ThreadHeartbeat()
    file_hb = supervise.Heartbeat(str(tmp_path / "phb"))
    supervise.bind_heartbeat(thread_hb)
    supervise.set_process_heartbeat(file_hb)
    try:
        supervise.touch_heartbeat("solver.phase.device")
    finally:
        supervise.bind_heartbeat(None)
        supervise.set_process_heartbeat(None)
    assert thread_hb.label() == "solver.phase.device"
    assert file_hb.read_label() == "solver.phase.device"


def test_wedge_verdict_names_the_phase(tmp_path):
    """A worker whose last labeled touch was a phase mark dies with that
    phase in the SuperviseResult AND the human-readable note."""
    hb = str(tmp_path / "hb")
    res = supervise.run_supervised(
        _script(f"""
            import time
            import sys
            sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
            from karpenter_core_tpu.utils import supervise
            supervise.Heartbeat({hb!r}).touch("solver.phase.device")
            time.sleep(60)  # the wedge: silence mid-device
        """),
        timeout_s=30.0, heartbeat_path=hb, stale_after_s=1.0, poll_s=0.1,
    )
    assert res.wedged
    assert res.phase == "solver.phase.device"
    assert "during solver.phase.device" in res.note
    assert res.wedge_log()["phase"] == "solver.phase.device"
