"""Port of reference scheduling suite_test.go — Instance Type Compatibility
+ Binpacking describes (suite_test.go:717-1253), spec-for-spec over the
expectations harness. Cited line numbers refer to
/root/reference/pkg/controllers/provisioning/scheduling/suite_test.go.
"""
import pytest

from karpenter_core_tpu.api import labels as api_labels
from karpenter_core_tpu.cloudprovider import fake
from karpenter_core_tpu.cloudprovider.types import Offering
from karpenter_core_tpu.kube.objects import (
    LABEL_ARCH_STABLE,
    LABEL_INSTANCE_TYPE_STABLE,
    LABEL_OS_STABLE,
    LABEL_TOPOLOGY_ZONE,
    NodeSelectorRequirement,
    NodeSelectorTerm,
)
from karpenter_core_tpu.testing import make_pod, make_provisioner
from karpenter_core_tpu.testing.expectations import Env

GI = 2**30
ZONE = LABEL_TOPOLOGY_ZONE
ITYPE = LABEL_INSTANCE_TYPE_STABLE
ARCH = LABEL_ARCH_STABLE


@pytest.fixture()
def env():
    return Env()


def req(key, op, *values):
    return NodeSelectorRequirement(key=key, operator=op, values=list(values))


def terms(*exprs):
    return [NodeSelectorTerm(match_expressions=list(exprs))]


def distinct_nodes(env, pods):
    names = set()
    for pod in pods:
        names.add(env.expect_scheduled(pod).metadata.name)
    return names


# -- Instance Type Compatibility (suite_test.go:717-976) --------------------


def test_more_resources_than_any_type_not_scheduled(env):
    """suite_test.go:718-728."""
    env.expect_applied(make_provisioner(name="default"))
    pod = make_pod(requests={"cpu": "512"})
    env.expect_provisioned(pod)
    env.expect_not_scheduled(pod)


def test_different_archs_on_different_instances(env):
    """suite_test.go:729-751."""
    env.expect_applied(
        make_provisioner(name="default", requirements=[req(ARCH, "In", "arm64", "amd64")])
    )
    pods = [
        make_pod(node_selector={ARCH: "amd64"}),
        make_pod(node_selector={ARCH: "arm64"}),
    ]
    env.expect_provisioned(*pods)
    assert len(distinct_nodes(env, pods)) == 2


def test_excludes_types_unsupported_by_pod_constraints_instance_type(env):
    """suite_test.go:752-770 — arm type conflicts with amd64-only provisioner."""
    env.expect_applied(
        make_provisioner(name="default", requirements=[req(ARCH, "In", "amd64")])
    )
    pod = make_pod(node_affinity_required=terms(req(ITYPE, "In", "arm-instance-type")))
    env.expect_provisioned(pod)
    env.expect_not_scheduled(pod)


def test_excludes_types_unsupported_by_pod_constraints_os(env):
    """suite_test.go:771-790 — the only ios-OS type is arm, disallowed."""
    env.expect_applied(
        make_provisioner(name="default", requirements=[req(ARCH, "In", "amd64")])
    )
    pod = make_pod(node_affinity_required=terms(req(LABEL_OS_STABLE, "In", "ios")))
    env.expect_provisioned(pod)
    env.expect_not_scheduled(pod)


def test_excludes_types_unsupported_by_provider_arch_constraint(env):
    """suite_test.go:791-803 — only the arm type has 14 cpu."""
    env.expect_applied(
        make_provisioner(name="default", requirements=[req(ARCH, "In", "amd64")])
    )
    pod = make_pod(limits={"cpu": "14"})
    env.expect_provisioned(pod)
    env.expect_not_scheduled(pod)


def test_different_operating_systems_on_different_instances(env):
    """suite_test.go:804-826."""
    env.expect_applied(
        make_provisioner(name="default", requirements=[req(ARCH, "In", "arm64", "amd64")])
    )
    pods = [
        make_pod(node_selector={LABEL_OS_STABLE: "linux"}),
        make_pod(node_selector={LABEL_OS_STABLE: "windows"}),
    ]
    env.expect_provisioned(*pods)
    assert len(distinct_nodes(env, pods)) == 2


def test_different_instance_type_selectors_on_different_instances(env):
    """suite_test.go:827-849."""
    env.expect_applied(
        make_provisioner(name="default", requirements=[req(ARCH, "In", "arm64", "amd64")])
    )
    pods = [
        make_pod(node_selector={ITYPE: "small-instance-type"}),
        make_pod(node_selector={ITYPE: "default-instance-type"}),
    ]
    env.expect_provisioned(*pods)
    assert len(distinct_nodes(env, pods)) == 2


def test_different_zone_selectors_on_different_instances(env):
    """suite_test.go:850-872."""
    env.expect_applied(
        make_provisioner(name="default", requirements=[req(ARCH, "In", "arm64", "amd64")])
    )
    pods = [
        make_pod(node_selector={ZONE: "test-zone-1"}),
        make_pod(node_selector={ZONE: "test-zone-2"}),
    ]
    env.expect_provisioned(*pods)
    assert len(distinct_nodes(env, pods)) == 2


def test_disjoint_extended_resources_on_different_instances():
    """suite_test.go:873-901 — no type has both GPUs."""
    universe = fake.instance_types(5)
    universe[0].capacity["karpenter.sh/super-great-gpu"] = 25.0
    universe[1].capacity["karpenter.sh/even-better-gpu"] = 25.0
    env = Env(universe=universe)
    env.expect_applied(make_provisioner(name="default"))
    pods = [
        make_pod(limits={"karpenter.sh/super-great-gpu": "1"}),
        make_pod(limits={"karpenter.sh/even-better-gpu": "1"}),
    ]
    env.expect_provisioned(*pods)
    assert len(distinct_nodes(env, pods)) == 2


def test_conjoint_extended_resources_not_schedulable():
    """suite_test.go:902-919 — one pod needing both GPUs fails."""
    universe = fake.instance_types(5)
    universe[0].capacity["karpenter.sh/super-great-gpu"] = 25.0
    universe[1].capacity["karpenter.sh/even-better-gpu"] = 25.0
    env = Env(universe=universe)
    env.expect_applied(make_provisioner(name="default"))
    pod = make_pod(
        limits={"karpenter.sh/super-great-gpu": "1", "karpenter.sh/even-better-gpu": "1"}
    )
    env.expect_provisioned(pod)
    env.expect_not_scheduled(pod)


# -- Provider Specific Labels (suite_test.go:920-975) -----------------------


def test_filters_types_matching_provider_labels():
    """suite_test.go:921-933 — size label selects ladder ends."""
    env = Env(universe=fake.instance_types(5))
    env.expect_applied(make_provisioner(name="default"))
    pods = [
        make_pod(node_selector={fake.LABEL_INSTANCE_SIZE: "large"}),
        make_pod(node_selector={fake.LABEL_INSTANCE_SIZE: "small"}),
    ]
    env.expect_provisioned(*pods)
    assert env.expect_scheduled(pods[0]).metadata.labels[ITYPE] == "fake-it-4"
    assert env.expect_scheduled(pods[1]).metadata.labels[ITYPE] == "fake-it-0"


def test_incompatible_provider_labels_not_scheduled():
    """suite_test.go:934-950."""
    universe = fake.instance_types(5)
    env = Env(universe=universe)
    env.expect_applied(make_provisioner(name="default"))
    pods = [
        make_pod(
            node_selector={fake.LABEL_INSTANCE_SIZE: "large", ITYPE: universe[0].name}
        ),
        make_pod(
            node_selector={fake.LABEL_INSTANCE_SIZE: "small", ITYPE: universe[4].name}
        ),
    ]
    env.expect_provisioned(*pods)
    env.expect_not_scheduled(pods[0])
    env.expect_not_scheduled(pods[1])


def test_optional_label_exists():
    """suite_test.go:951-962 — Exists on a label only some types carry."""
    universe = fake.instance_types(5)
    env = Env(universe=universe)
    env.expect_applied(make_provisioner(name="default"))
    pod = make_pod(
        node_affinity_required=terms(req(fake.EXOTIC_INSTANCE_LABEL_KEY, "Exists"))
    )
    env.expect_provisioned(pod)
    node = env.expect_scheduled(pod)
    assert fake.EXOTIC_INSTANCE_LABEL_KEY in node.metadata.labels
    assert node.metadata.labels[ITYPE] == universe[4].name


def test_optional_label_does_not_exist():
    """suite_test.go:963-974."""
    env = Env(universe=fake.instance_types(5))
    env.expect_applied(make_provisioner(name="default"))
    pod = make_pod(
        node_affinity_required=terms(req(fake.EXOTIC_INSTANCE_LABEL_KEY, "DoesNotExist"))
    )
    env.expect_provisioned(pod)
    node = env.expect_scheduled(pod)
    assert fake.EXOTIC_INSTANCE_LABEL_KEY not in node.metadata.labels


# -- Binpacking (suite_test.go:977-1253) ------------------------------------


def test_small_pod_on_smallest_instance(env):
    """suite_test.go:978-989."""
    env.expect_applied(make_provisioner(name="default"))
    pod = make_pod(requests={"memory": "100M"})
    env.expect_provisioned(pod)
    assert env.expect_scheduled(pod).metadata.labels[ITYPE] == "small-instance-type"


def test_small_pod_on_smallest_possible_instance(env):
    """suite_test.go:990-1001."""
    env.expect_applied(make_provisioner(name="default"))
    pod = make_pod(requests={"memory": "2000M"})
    env.expect_provisioned(pod)
    assert env.expect_scheduled(pod).metadata.labels[ITYPE] == "small-instance-type"


def test_multiple_small_pods_share_smallest_instance(env):
    """suite_test.go:1002-1020."""
    env.expect_applied(make_provisioner(name="default"))
    pods = [make_pod(requests={"memory": "10M"}) for _ in range(5)]
    env.expect_provisioned(*pods)
    names = set()
    for pod in pods:
        node = env.expect_scheduled(pod)
        names.add(node.metadata.name)
        assert node.metadata.labels[ITYPE] == "small-instance-type"
    assert len(names) == 1


def test_new_nodes_when_at_capacity(env):
    """suite_test.go:1021-1040 — 40 x 1.8G pods -> 20 default nodes."""
    env.expect_applied(make_provisioner(name="default"))
    pods = [
        make_pod(node_selector={ARCH: "amd64"}, requests={"memory": "1.8G"})
        for _ in range(40)
    ]
    env.expect_provisioned(*pods)
    names = set()
    for pod in pods:
        node = env.expect_scheduled(pod)
        names.add(node.metadata.name)
        assert node.metadata.labels[ITYPE] == "default-instance-type"
    assert len(names) == 20


def test_packs_small_and_large_pods_together(env):
    """suite_test.go:1041-1072."""
    env.expect_applied(make_provisioner(name="default"))
    large = [
        make_pod(node_selector={ARCH: "amd64"}, requests={"memory": "1.8G"})
        for _ in range(40)
    ]
    small = [
        make_pod(node_selector={ARCH: "amd64"}, requests={"memory": "400M"})
        for _ in range(20)
    ]
    pods = large + small
    env.expect_provisioned(*pods)
    names = set()
    for pod in pods:
        node = env.expect_scheduled(pod)
        names.add(node.metadata.name)
        assert node.metadata.labels[ITYPE] == "default-instance-type"
    assert len(names) == 20


def test_packs_nodes_tightly():
    """suite_test.go:1073-1098 — big pod then small pod get different sizes."""
    env = Env(universe=fake.instance_types(5))
    env.expect_applied(make_provisioner(name="default"))
    pods = [
        make_pod(requests={"cpu": "4.5"}),
        make_pod(requests={"cpu": "1"}),
    ]
    env.expect_provisioned(*pods)
    node1 = env.expect_scheduled(pods[0])
    node2 = env.expect_scheduled(pods[1])
    assert node1.metadata.labels[ITYPE] != node2.metadata.labels[ITYPE]


def test_zero_quantity_resource_requests(env):
    """suite_test.go:1099-1110."""
    env.expect_applied(make_provisioner(name="default"))
    pod = make_pod(
        requests={"foo.com/weird-resources": "0"},
        limits={"foo.com/weird-resources": "0"},
    )
    env.expect_provisioned(pod)
    env.expect_scheduled(pod)


def test_pod_exceeding_every_capacity_not_scheduled(env):
    """suite_test.go:1111-1121."""
    env.expect_applied(make_provisioner(name="default"))
    pod = make_pod(requests={"memory": "2Ti"})
    env.expect_provisioned(pod)
    env.expect_not_scheduled(pod)


def test_new_nodes_on_pod_count_limit(env):
    """suite_test.go:1122-1143 — 25 tiny pods, 5-pod cap -> 5 small nodes."""
    env.expect_applied(make_provisioner(name="default"))
    pods = [
        make_pod(
            node_selector={ARCH: "amd64"}, requests={"memory": "1M", "cpu": "1m"}
        )
        for _ in range(25)
    ]
    env.expect_provisioned(*pods)
    names = set()
    for pod in pods:
        node = env.expect_scheduled(pod)
        names.add(node.metadata.name)
        assert node.metadata.labels[ITYPE] == "small-instance-type"
    assert len(names) == 5


def test_init_container_requests_counted(env):
    """suite_test.go:1144-1164 — init ceiling forces the bigger type."""
    env.expect_applied(make_provisioner(name="default"))
    pod = make_pod(
        requests={"memory": "1Gi", "cpu": "1"},
        init_requests={"memory": "1Gi", "cpu": "2"},
    )
    env.expect_provisioned(pod)
    assert env.expect_scheduled(pod).metadata.labels[ITYPE] == "default-instance-type"


def test_init_container_requests_exceeding_capacity_not_scheduled(env):
    """suite_test.go:1165-1184."""
    env.expect_applied(make_provisioner(name="default"))
    pod = make_pod(
        requests={"memory": "1Gi", "cpu": "1"},
        init_requests={"memory": "1Ti", "cpu": "2"},
    )
    env.expect_provisioned(pod)
    env.expect_not_scheduled(pod)


def test_selects_valid_types_regardless_of_price():
    """suite_test.go:1185-1252 — cheapest valid type wins; all valid options
    are passed to the cloud provider."""
    universe = [
        fake.new_instance_type(
            "medium",
            resources={"cpu": 2.0, "memory": 2.0 * GI},
            offerings=[Offering("on-demand", "test-zone-1a", 3.0)],
        ),
        fake.new_instance_type(
            "small",
            resources={"cpu": 1.0, "memory": 1.0 * GI},
            offerings=[Offering("on-demand", "test-zone-1a", 2.0)],
        ),
        fake.new_instance_type(
            "large",
            resources={"cpu": 4.0, "memory": 4.0 * GI},
            offerings=[Offering("on-demand", "test-zone-1a", 1.0)],
        ),
    ]
    env = Env(universe=universe)
    env.expect_applied(make_provisioner(name="default"))
    pod = make_pod(limits={"cpu": "1m", "memory": "1Mi"})
    env.expect_provisioned(pod)
    node = env.expect_scheduled(pod)
    assert node.metadata.labels[ITYPE] == "large"
    create_reqs = {
        r.key: set(r.values)
        for r in env.cloud_provider.create_calls[0].spec.requirements
    }
    assert create_reqs[ITYPE] == {"small", "medium", "large"}
