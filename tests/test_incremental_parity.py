"""Incremental-vs-full prescreen parity over seeded churn SEQUENCES
(ISSUE 6 acceptance).

The delta re-solve path (solver/incremental.py + ops/pack.py
make_screen_refresh_kernel) must be a pure DISPATCH optimization: across a
sequence of consecutive solves whose world drifts the way sustained churn
drifts it — new items, freed slots, narrowed slots — the incremental
solver's placements must be byte-identical (flightrec-canonical JSON, the
test_screen_parity.py bar) to a solver that runs the full [N, C] verdict
precompute every time. Sequences matter: a one-shot comparison can't catch
a stale resident tensor, a fingerprint that missed a plane, or an
adopt/plan pairing bug — those only show up on solve k+1.

Also covers the degrade contract: a chaos `state.diff` feed fault must
force the full path for that solve (never a drifted refresh) and drop
residency, with parity still holding.
"""
import copy

import numpy as np
import pytest

from karpenter_core_tpu import chaos
from karpenter_core_tpu.api.labels import (
    LABEL_CAPACITY_TYPE,
    LABEL_NODE_INITIALIZED,
    PROVISIONER_NAME_LABEL_KEY,
)
from karpenter_core_tpu.cloudprovider import fake
from karpenter_core_tpu.kube.objects import (
    LABEL_INSTANCE_TYPE_STABLE,
    LABEL_TOPOLOGY_ZONE,
    LabelSelector,
    PodAffinityTerm,
    TopologySpreadConstraint,
    object_key,
)
from karpenter_core_tpu.obs import flightrec
from karpenter_core_tpu.obs.flightrec import canonical_placements, placements_json
from karpenter_core_tpu.solver.incremental import (
    DiffGate,
    IncrementalScreen,
    MAX_ROW_DELTA,
)
from karpenter_core_tpu.solver.tpu_solver import TPUSolver
from karpenter_core_tpu.kube.client import InMemoryKubeClient
from karpenter_core_tpu.state.cluster import Cluster
from karpenter_core_tpu.state.node import StateNode
from karpenter_core_tpu.testing import make_node, make_pod, make_provisioner

ZONES = ["test-zone-1", "test-zone-2", "test-zone-3"]
APPS = [f"churn-{i}" for i in range(6)]
HOSTNAME_KEY = "kubernetes.io/hostname"


def _anchor_pods():
    """One pod per vocabulary value: the dictionary (and the compiled
    geometry) is identical across every step and seed, which is exactly
    the steady-state regime the incremental path exists for."""
    spread = TopologySpreadConstraint(
        max_skew=2,
        topology_key=HOSTNAME_KEY,
        when_unsatisfiable="DoNotSchedule",
        label_selector=LabelSelector(match_labels={"app": APPS[0]}),
    )
    anti = PodAffinityTerm(
        topology_key=HOSTNAME_KEY,
        label_selector=LabelSelector(match_labels={"app": APPS[1]}),
    )
    pods = [make_pod(labels={"app": a}, requests={"cpu": "0.1"}) for a in APPS]
    pods.append(
        make_pod(labels={"app": APPS[0]}, requests={"cpu": "0.1"},
                 topology_spread=[spread])
    )
    pods.append(
        make_pod(labels={"app": APPS[1]}, requests={"cpu": "0.1"},
                 pod_anti_affinity_required=[anti])
    )
    return pods


def _filler_pods(rng, n):
    return [
        make_pod(
            labels={"app": APPS[int(rng.integers(len(APPS)))]},
            requests={"cpu": str(float(rng.choice([0.25, 0.5, 1.0])))},
        )
        for _ in range(n)
    ]


def _nodes(universe, count=10):
    out = []
    for e in range(count):
        it = universe[e % len(universe)]
        out.append(
            StateNode(
                node=make_node(
                    name=f"churn-node-{e}",
                    labels={
                        PROVISIONER_NAME_LABEL_KEY: "default",
                        LABEL_NODE_INITIALIZED: "true",
                        LABEL_INSTANCE_TYPE_STABLE: it.name,
                        LABEL_CAPACITY_TYPE: "on-demand",
                        LABEL_TOPOLOGY_ZONE: ZONES[e % 3],
                    },
                    capacity={k: str(v) for k, v in it.capacity.items()},
                )
            )
        )
    return out


class ChurnSequence:
    """Deterministic sequence of (pods, state_nodes) solve inputs whose
    node planes drift between steps the way churn drifts them: each step
    BINDS a few pods onto random nodes (narrowed slots) and UNBINDS a few
    previously bound ones (freed slots), over a fixed node count and a
    fixed label vocabulary — so the geometry key is stable and only the
    plane CONTENT changes."""

    def __init__(self, seed, node_count=10, filler=6, grow_to=13):
        self.rng = np.random.default_rng(seed)
        self.universe = fake.instance_types(6)
        self.nodes = _nodes(self.universe, node_count)
        self.filler = filler
        self.grow_to = grow_to
        self.bound = []  # (node index, pod key) in bind order
        self._n = 0
        self._step = 0

    def step(self):
        self._step += 1
        # a growing cluster inside one existing-axis bucket: new nodes
        # exercise the hostname pad-rebinding adoption path (a launch must
        # not re-mint the geometry out from under the resident tensor)
        if self._step % 2 == 0 and len(self.nodes) < self.grow_to:
            self.nodes.append(_nodes(self.universe, len(self.nodes) + 1)[-1])
        # churn the node planes: unbind up to 2 oldest, bind 2 fresh
        for _ in range(min(2, len(self.bound))):
            e, key = self.bound.pop(0)
            self.nodes[e].cleanup_for_pod(key)
        for _ in range(2):
            e = int(self.rng.integers(len(self.nodes)))
            self._n += 1
            p = make_pod(
                name=f"bound-{self._n}",
                labels={"app": APPS[int(self.rng.integers(len(APPS)))]},
                requests={"cpu": "0.25"},
            )
            self.nodes[e].update_for_pod(p)
            self.bound.append((e, object_key(p)))
        pods = _anchor_pods() + _filler_pods(self.rng, self.filler)
        return pods, [n.deep_copy() for n in self.nodes]


def _solve(solver, pods, nodes, its, provisioners, cluster=None):
    res = solver.solve(
        copy.deepcopy(pods), provisioners, its, state_nodes=nodes,
        cluster=cluster,
    )
    return placements_json(canonical_placements(res)), res


def _parity_run(seed, steps, cluster=None, inc_solver=None):
    """Drive both solvers through one churn sequence; returns the list of
    prescreen modes the incremental solver took per step."""
    seq = ChurnSequence(seed)
    provisioners = [make_provisioner(name="default")]
    its = {"default": seq.universe}
    inc = inc_solver or TPUSolver(
        max_nodes=64, screen_mode="prescreen", incremental="on"
    )
    full = TPUSolver(max_nodes=64, screen_mode="prescreen", incremental="off")
    modes = []
    for k in range(steps):
        pods, nodes = seq.step()
        a, res_a = _solve(inc, pods, [n.deep_copy() for n in nodes], its,
                          provisioners, cluster=cluster)
        b, res_b = _solve(full, pods, nodes, its, provisioners)
        if a != b:
            diff = flightrec.diff_placements(
                canonical_placements(res_a), canonical_placements(res_b)
            )
            raise AssertionError(
                f"incremental diverged from full at churn step {k}:\n"
                + "\n".join(diff)
            )
        assert res_a.rounds == res_b.rounds
        assert len(res_a.failed_pods) == len(res_b.failed_pods)
        modes.append(inc.last_prescreen_mode)
    return modes


@pytest.mark.parametrize("seed", [3, 17, 41])
def test_incremental_parity_churn_sequence(seed):
    """Seeded churn sequences through both paths: byte-identical placements
    at EVERY step, and the delta refresh must actually engage (a suite
    where the incremental path silently always ran full would be testing
    nothing)."""
    modes = _parity_run(seed, steps=6)
    assert modes[0] == "full", "first solve has nothing resident"
    assert modes.count("refresh") >= 3, (
        f"delta re-solve never settled in: modes={modes}"
    )


def test_incremental_degrades_under_state_diff_chaos():
    """chaos `state.diff` feed faults force the FULL path for the faulted
    solve (degrade, never a drifted refresh) — parity still holds on every
    step, residency is dropped, and the path re-engages after the fault
    clears."""
    cluster = Cluster(InMemoryKubeClient())
    inc = TPUSolver(max_nodes=64, screen_mode="prescreen", incremental="on")
    try:
        # solves 3+ see a dead feed for 2 consults: plan() must dispatch
        # full for those solves even though the planes barely moved
        chaos.arm(chaos.STATE_DIFF, error="conn", probability=1.0,
                  after=2, times=2, seed=7)
        modes = _parity_run(11, steps=6, cluster=cluster, inc_solver=inc)
    finally:
        chaos.disarm(chaos.STATE_DIFF)
    assert "refresh" in modes, f"never refreshed around the fault: {modes}"
    # the two faulted consults forced full even under a stable geometry
    assert modes.count("full") >= 3, f"fault did not degrade: {modes}"
    assert modes[-1] == "refresh", (
        f"path did not recover after the fault cleared: {modes}"
    )


def test_incremental_plan_outcomes_unit(monkeypatch):
    """IncrementalScreen.plan outcome ladder on synthetic planes: miss
    (nothing resident) -> refresh with exact changed-row/col indices ->
    full_wide past the delta budget -> full_gated drops residency."""
    rng = np.random.default_rng(0)
    E, C, V = 12, 9, 40

    def planes():
        exist = {
            k: rng.integers(0, 2, size=(E, V)).astype(bool)
            for k in ("allow", "out", "defined")
        }
        pods = {
            k: rng.integers(0, 2, size=(C, V)).astype(bool)
            for k in ("allow", "out", "defined", "escape", "custom_deny")
        }
        pods["scls_first"] = np.arange(C, dtype=np.int32)
        return pods, exist

    pods, exist = planes()
    inc = IncrementalScreen()
    key = ("geom", "prescreen")

    assert inc.plan(key, pods, exist) is None  # nothing resident yet
    inc.adopt(key, screen_dev="tensor-0")
    assert inc.resident(key) == "tensor-0"

    # identical planes: an EMPTY refresh (carry the tensor over as-is)
    delta = inc.plan(key, pods, exist)
    assert delta is not None and len(delta.rows) == 0 and len(delta.cols) == 0
    inc.adopt(key, "tensor-1")

    # narrow drift: exactly the touched rows/cols, budgets pow2-padded
    exist["allow"][4] = ~exist["allow"][4]
    exist["defined"][7] = ~exist["defined"][7]
    pods["out"][2] = ~pods["out"][2]
    delta = inc.plan(key, pods, exist)
    assert delta is not None
    assert list(delta.rows) == [4, 7]
    assert list(delta.cols) == [2]
    assert delta.rb >= 2 and delta.cb >= 1
    row_idx, row_n, col_idx, col_n = delta.padded()
    assert len(row_idx) == delta.rb and row_n == 2
    assert list(row_idx[:2]) == [4, 7]
    inc.adopt(key, "tensor-2")

    # wide drift: past the (narrowed) row budget -> full, residency kept
    # (the full precompute that follows re-adopts at the same key)
    from karpenter_core_tpu.solver import incremental as inc_mod

    monkeypatch.setattr(inc_mod, "MAX_ROW_DELTA", 4)
    wide_exist = {k: ~v for k, v in exist.items()}
    assert inc.plan(key, pods, wide_exist) is None
    monkeypatch.setattr(inc_mod, "MAX_ROW_DELTA", MAX_ROW_DELTA)

    # feed fault with residency: full_gated AND residency dropped
    assert inc.plan(key, pods, exist, gate_ok=False) is None
    assert inc.resident(key) is None

    # adopt without a matching plan leaves the carrier empty, not paired
    # with stale fingerprints
    inc.adopt(("other", "key"), "tensor-3")
    assert inc.resident(("other", "key")) is None


def test_cluster_changes_since_feed_semantics():
    """The state-store delta feed: dense revisions, set-collapsed tokens,
    full-resync verdicts for unknown cursors and ring-gap history."""
    c = Cluster(InMemoryKubeClient())
    cur, changed = c.changes_since(None)
    assert changed is None  # no cursor: cannot prove history

    n = make_node(name="n-1", labels={}, provider_id="fake:///n-1")
    c.update_node(n)
    cur2, changed = c.changes_since(cur)
    assert changed == {"fake:///n-1"}
    assert cur2 > cur

    # caught-up cursor: provably empty delta, NOT a resync
    cur3, changed = c.changes_since(cur2)
    assert cur3 == cur2 and changed == set()

    # duplicated churn collapses (at-least-once delivery is a set)
    c.update_node(n)
    c.update_node(n)
    _, changed = c.changes_since(cur2)
    assert changed == {"fake:///n-1"}

    # a cursor from the future (restarted store) is a resync
    _, changed = c.changes_since(cur2 + 10_000)
    assert changed is None

    # history falling off the bounded ring is DETECTED, never skipped
    c2 = Cluster(InMemoryKubeClient())
    base, _ = c2.changes_since(None)
    for i in range(c2.CHANGE_RING + 5):
        c2.update_node(make_node(name=f"m-{i}", provider_id=f"fake:///m-{i}"))
    _, changed = c2.changes_since(base)
    assert changed is None


def test_diff_gate_consumes_feed_and_degrades_on_fault():
    c = Cluster(InMemoryKubeClient())
    gate = DiffGate()
    assert gate.gate(c) is False  # first consult: no cursor yet
    assert gate.gate(c) is True  # continuous (empty) history
    c.update_node(make_node(name="g-1", provider_id="fake:///g-1"))
    assert gate.gate(c) is True  # continuous non-empty history
    try:
        chaos.arm(chaos.STATE_DIFF, error="conn", probability=1.0, times=1)
        assert gate.gate(c) is False  # injected feed fault
    finally:
        chaos.disarm(chaos.STATE_DIFF)
    # the fault reset the cursor: the next consult must re-prove history
    assert gate.gate(c) is False
    assert gate.gate(c) is True
    # objects with no feed at all (gRPC boundary) stay reuse-allowed:
    # plane fingerprints alone are exact
    assert gate.gate(object()) is True
    assert gate.gate(None) is True
