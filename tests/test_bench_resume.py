"""Resumable stage-graph bench orchestration (ISSUE 11): plan/merge over a
fake round dir — partial artifacts resume correctly, degraded columns
re-run, fallback columns re-run only when the TPU verdict is back, and the
merged JSON is byte-stable and schema-complete. No subprocesses, no jax:
these drive the pure planning/merging layer the orchestrator is built on."""
import json

import pytest

import bench
from karpenter_core_tpu.utils import supervise


@pytest.fixture()
def store(tmp_path):
    return supervise.ArtifactStore(str(tmp_path / "stages"))


def _save_ok(store, name, data=None, **kwargs):
    store.save(name, bench.stage_config(name), data or {"v": 1}, **kwargs)


HEADLINE_DATA = {
    "pods": bench.N_PODS, "types": bench.N_TYPES,
    "distinct": bench.N_DISTINCT, "existing": bench.N_EXISTING,
    "pods_per_sec": 480.4, "e2e_p50_ms": 263.3, "e2e_p99_ms": 416.3,
    "device_solve_med_ms": 1.4, "device_p50_ms_varied": 5.1,
    "device_p99_ms_varied": 5.6, "runs": 2,
    "tail": {"e2e_sorted_ms": [107.3, 419.4]},
    "scheduled_min": 191, "compile_cold_s": 8.1, "bucket_hit_ratio": 1.0,
    "compiled_programs_after_varied_batches": 2, "solver": "TPUSolver",
    "chips": 1, "cpu_fallback": False,
}

# every column the historical BENCH_r{N}.json schema carries — the merge
# must emit ALL of them no matter which stages degraded (plus the new
# stage bookkeeping columns)
EXPECTED_EXTRA_KEYS = {
    "e2e_p50_ms", "e2e_p99_ms", "device_solve_med_ms", "device_p50_ms_varied",
    "device_p99_ms_varied", "pipelined_p50_ms", "pipelined_p99_ms",
    "pipelined_runs", "north_star_target_ms", "single_call_under_target",
    "pipelined_under_target", "device_under_target", "runs", "tail",
    "scheduled_min", "compile_cold_s", "first_solve_warm_s",
    "warm_restart_cache_verified", "warm_restart_under_2s",
    "bucket_hit_ratio", "warm_restart",
    "compiled_programs_after_varied_batches", "solver", "sharded_speedup",
    "mesh", "multichip", "chips", "backend_probe", "consolidation",
    "consolidation_xl", "consolidation_under_1s", "config5_multiprov_spot_od",
    "config_grid_1_2_3", "stages", "round_dir",
}


def _fill_round(store, degraded=(), fallback=()):
    """A complete fake round with the named stages degraded/fallback."""
    for name in bench.STAGE_NAMES:
        cfg = bench.stage_config(name)
        if name in degraded:
            store.save(name, cfg, None, degraded=True, error="wedged",
                       wedge_log={"note": "killed", "wedged": True,
                                  "stderr_tail": "last lines"})
        elif name == "headline":
            store.save(name, cfg, dict(HEADLINE_DATA),
                       fallback=name in fallback,
                       meta={"backend": "TPU v5e", "platform": "tpu"})
        else:
            store.save(name, cfg, {"v": 1}, fallback=name in fallback,
                       meta={"backend": "TPU v5e", "platform": "tpu"})


# ---------------------------------------------------------------------------
# planning


def test_plan_empty_store_runs_everything_in_graph_order(store):
    assert bench.plan_stages(store, tpu_available=True) == list(
        bench.STAGE_NAMES
    )


def test_plan_skips_fresh_artifacts(store):
    _fill_round(store)
    assert bench.plan_stages(store, tpu_available=True) == []


def test_plan_reruns_missing_and_degraded_only(store):
    _fill_round(store, degraded=("consolidation",))
    # remove one artifact entirely: "missing" and "degraded" both re-run
    import os

    os.unlink(store.path("grid"))
    assert bench.plan_stages(store, tpu_available=False) == [
        "grid", "consolidation",
    ]


def test_plan_reruns_fallback_columns_only_when_tpu_is_back(store):
    """An involuntary-CPU column is complete data — kept while the tunnel
    is down, re-run the moment the verdict says the TPU returned (the
    point of --resume after a wedged round)."""
    _fill_round(store, fallback=("multichip", "headline"))
    assert bench.plan_stages(store, tpu_available=False) == []
    assert bench.plan_stages(store, tpu_available=True) == [
        "headline", "multichip",
    ]


def test_plan_config_digest_change_invalidates(store):
    _fill_round(store)
    # a different geometry mints a different digest: the artifact no
    # longer answers the question being asked
    rec = store.load("headline")
    rec["config_digest"] = "0" * 16
    supervise.atomic_write_json(store.path("headline"), rec)
    assert bench.plan_stages(store, tpu_available=False) == ["headline"]


def test_plan_env_skip_writes_completed_skip_artifact(store, monkeypatch):
    monkeypatch.setenv("BENCH_STAGES", "headline,consolidation")
    todo = bench.plan_stages(store, tpu_available=True)
    assert todo == ["headline", "consolidation"]
    rec = store.load("grid")
    assert rec is not None and not rec["degraded"]
    assert "not in BENCH_STAGES" in rec["data"]["skipped"]
    # merged schema stays full: the skipped stages carry their marker
    merged = bench.merge_round(store)
    assert merged["extra"]["stages"]["grid"]["status"] == "skipped"


def test_plan_legacy_skip_envs(store, monkeypatch):
    monkeypatch.setenv("BENCH_SKIP_CONSOLIDATION", "1")
    todo = bench.plan_stages(store, tpu_available=True)
    assert "consolidation" not in todo and "consolidation_xl" not in todo
    assert "headline" in todo


# ---------------------------------------------------------------------------
# merging


def test_merge_complete_round_schema_and_metric(store):
    _fill_round(store)
    merged = bench.merge_round(store, round_dir="/r")
    assert merged["metric"].startswith("pods_per_sec_e2e_p99_")
    assert merged["value"] == HEADLINE_DATA["pods_per_sec"]
    assert merged["unit"] == "pods/sec"
    missing = EXPECTED_EXTRA_KEYS - set(merged["extra"])
    assert not missing, f"schema incomplete: {sorted(missing)}"
    assert merged["extra"]["single_call_under_target"] is True
    assert all(
        s["status"] == "ok" for s in merged["extra"]["stages"].values()
    )


def test_merge_degraded_stage_yields_marked_column_with_wedge_log(store):
    _fill_round(store, degraded=("consolidation",))
    merged = bench.merge_round(store)
    cons = merged["extra"]["consolidation"]
    assert cons["degraded"] is True
    assert cons["wedge_log"]["stderr_tail"] == "last lines"
    assert merged["extra"]["stages"]["consolidation"]["status"] == "degraded"
    # a degraded consolidation_xl nulls its derived scalar, nothing else
    assert merged["extra"]["e2e_p99_ms"] == HEADLINE_DATA["e2e_p99_ms"]
    missing = EXPECTED_EXTRA_KEYS - set(merged["extra"])
    assert not missing, "degradation must not drop columns"


def test_merge_degraded_headline_still_emits_full_schema(store):
    _fill_round(store, degraded=("headline",))
    merged = bench.merge_round(store)
    assert merged["metric"].startswith("bench_failed_")
    assert merged["value"] == 0.0
    missing = EXPECTED_EXTRA_KEYS - set(merged["extra"])
    assert not missing
    assert merged["extra"]["e2e_p99_ms"] is None
    assert merged["extra"]["single_call_under_target"] is False


def test_merge_is_byte_stable(store):
    """Merging the same round dir twice is byte-identical — the merge is
    pure over the artifacts (resume-then-remerge can't churn the JSON)."""
    _fill_round(store, degraded=("grid",), fallback=("multichip",))
    a = json.dumps(bench.merge_round(store, round_dir="/r"), sort_keys=True)
    b = json.dumps(bench.merge_round(store, round_dir="/r"), sort_keys=True)
    assert a == b


def test_merge_fallback_column_is_marked(store):
    _fill_round(store, fallback=("consolidation",))
    merged = bench.merge_round(store)
    assert merged["extra"]["consolidation"]["cpu_fallback_column"] is True
    assert merged["extra"]["stages"]["consolidation"]["status"] == "fallback"


def test_merge_warm_restart_validity_gates_the_under_2s_claim(store):
    """A warm-restart worker on a DIFFERENT platform than the headline
    (the r05 failure mode) must not claim the restart-stall number."""
    _fill_round(store)
    wr_cfg = bench.stage_config("warm_restart")
    good = {"first_solve_s": 1.2, "cache_files": 10, "platform": "tpu",
            "pods": bench.N_PODS}
    store.save("warm_restart", wr_cfg, good,
               meta={"backend": "TPU v5e", "platform": "tpu"})
    merged = bench.merge_round(store)
    assert merged["extra"]["warm_restart_under_2s"] is True
    store.save("warm_restart", wr_cfg, dict(good, platform="cpu"),
               meta={"backend": "cpu-fallback", "platform": "cpu"})
    merged = bench.merge_round(store)
    assert merged["extra"]["warm_restart_under_2s"] is False
    assert merged["extra"]["warm_restart_cache_verified"] is False
    assert merged["extra"]["first_solve_warm_s"] == 1.2, (
        "the raw number still lands; only the claim is gated"
    )


def test_merge_salvaged_wedge_log_rides_a_completed_column(store):
    """A stage that printed its result then hung at exit completes WITH
    its wedge log attached (the salvage path)."""
    _fill_round(store)
    store.save(
        "pipelined", bench.stage_config("pipelined"),
        {"pipelined_p99_ms": 900.0, "pipelined_p50_ms": 800.0,
         "pipelined_runs": 6},
        wedge_log={"note": "worker hung at exit, result salvaged",
                   "wedged": True},
        meta={"backend": "TPU v5e", "platform": "tpu"},
    )
    merged = bench.merge_round(store)
    col = merged["extra"]["config5_multiprov_spot_od"]
    assert "degraded" not in col
    assert merged["extra"]["pipelined_p99_ms"] == 900.0
    assert merged["extra"]["stages"]["pipelined"]["status"] == "ok"


# ---------------------------------------------------------------------------
# stage-scoped chaos grammar (the smoke's wedge-injection channel)


def test_stage_chaos_grammar(monkeypatch):
    monkeypatch.setenv(
        "BENCH_STAGE_CHAOS",
        "consolidation=solver.device.hang=error:none,latency:600,times:1"
        "|grid=solver.device=error:timeout",
    )
    assert bench._stage_chaos("consolidation") == (
        "solver.device.hang=error:none,latency:600,times:1"
    )
    assert bench._stage_chaos("grid") == "solver.device=error:timeout"
    assert bench._stage_chaos("headline") == ""


def test_stage_config_digests_are_stage_distinct():
    digests = {
        name: supervise.config_digest(bench.stage_config(name))
        for name in bench.STAGE_NAMES
    }
    assert len(set(digests.values())) == len(digests), (
        "every stage must key its own artifact"
    )


# ---------------------------------------------------------------------------
# round timeline (ISSUE 15): BENCH_timeline.json stitched purely from the
# artifacts — stage slices, worker trace fragments, wedge/resume markers


def _fragment(pid=4242):
    """A stage worker's wall-anchored chrome-trace fragment: events are in
    the worker's perf-counter timebase (µs since its tracer t0); the
    anchor pair lets the merge rebase them onto the wall clock."""
    return {
        "wall_anchor_s": 130.0, "anchor_ts_us": 5e6, "pid": pid,
        "events": [
            {"name": "solver.phase.device", "ph": "X", "ts": 4e6,
             "dur": 2e5, "pid": pid, "tid": 1, "args": {}},
            {"name": "bench.heartbeat", "ph": "i", "s": "p", "ts": 4.1e6,
             "pid": pid, "tid": 1, "args": {}},
        ],
        "dropped": 3,
    }


def _timeline_round(store):
    for name in bench.STAGE_NAMES:
        cfg = bench.stage_config(name)
        if name == "consolidation":
            store.save(
                name, cfg, None, degraded=True, error="wedged",
                wedge_log={"note": "wedged: heartbeat stale for 31s "
                                   "during solver.phase.device; "
                                   "process group killed",
                           "wedged": True, "timed_out": False,
                           "phase": "solver.phase.device",
                           "stdout_tail": "", "stderr_tail": ""},
                meta={"started_ts": 100.0, "ended_ts": 131.0},
            )
        elif name == "grid":
            store.save(name, cfg, {"v": 1},
                       meta={"started_ts": 140.0, "ended_ts": 150.0,
                             "resumed": True})
        else:
            store.save(name, cfg, {"v": 1},
                       meta={"started_ts": 90.0, "ended_ts": 130.0,
                             "trace": _fragment()})


def test_timeline_stitches_stages_fragments_and_markers(store):
    _timeline_round(store)
    tl = bench.build_timeline(store)
    events = tl["traceEvents"]
    names = [e["name"] for e in events]
    # one orchestrator slice per stage that ran
    for name in bench.STAGE_NAMES:
        if name != "consolidation":
            assert f"bench.stage.{name}" in names
    # the chaos-wedged stage's kill is VISIBLE, naming the phase
    kill = next(e for e in events if e["name"] == "bench.wedge.SIGKILL")
    assert kill["ph"] == "i"
    assert kill["args"]["stage"] == "consolidation"
    assert kill["args"]["phase"] == "solver.phase.device"
    assert kill["ts"] == (131.0 - 90.0) * 1e6
    # resume backfill marker on the resumed stage
    backfill = next(
        e for e in events if e["name"] == "bench.resume.backfill"
    )
    assert backfill["args"]["stage"] == "grid"
    # worker fragments rebase onto the wall clock and keep their pid row:
    # wall anchor 130 -> 40e6µs after base 90; offset 40e6-5e6 = 35e6
    dev = next(e for e in events if e["name"] == "solver.phase.device")
    assert dev["ts"] == 4e6 + 35e6
    assert dev["pid"] == 4242
    assert any(e["name"] == "bench.heartbeat" for e in events)
    # fragment truncation stays visible
    assert tl["otherData"]["dropped_events"] >= 3
    assert tl["otherData"]["stages"]["consolidation"] == "degraded"


def test_timeline_is_byte_stable_across_remerges(store):
    _timeline_round(store)
    a = json.dumps(bench.build_timeline(store), sort_keys=True)
    b = json.dumps(bench.build_timeline(store), sort_keys=True)
    assert a == b


# ---------------------------------------------------------------------------
# cross-round perf ledger (ISSUE 18): append/verdict are pure over the
# store + prior ledger dict — no subprocesses, no files unless a test
# wants one


def _row_keys(ledger):
    return [(r["round"], r["stage"], r["column"]) for r in ledger["rows"]]


def test_append_ledger_cold_start_and_byte_stable(store, tmp_path):
    _fill_round(store)
    # missing-ledger cold start: no file is no prior
    assert bench._load_ledger(str(tmp_path / "PERF_LEDGER.json")) is None
    l1 = bench.append_ledger(store, None, "r07")
    assert l1["version"] == bench.LEDGER_VERSION
    assert l1["rows"], "a complete round must contribute rows"
    # re-folding the same unchanged round over its own output is a no-op
    l2 = bench.append_ledger(store, l1, "r07")
    assert json.dumps(l1, sort_keys=True) == json.dumps(l2, sort_keys=True)
    # row identity is unique per (round, stage, column)
    assert len(set(_row_keys(l2))) == len(l2["rows"])


def test_append_ledger_rows_carry_provenance(store):
    _fill_round(store, fallback=("multichip",))
    hd = dict(HEADLINE_DATA, programs_digest="abc123def456")
    store.save("headline", bench.stage_config("headline"), hd,
               meta={"backend": "TPU v5e", "platform": "tpu"})
    ledger = bench.append_ledger(store, None, "r07")
    rows = {(r["stage"], r["column"]): r for r in ledger["rows"]}
    head = rows[("headline", "e2e_p99_ms")]
    assert head["value"] == HEADLINE_DATA["e2e_p99_ms"]
    assert head["platform"] == "tpu"
    assert head["programs_digest"] == "abc123def456"
    assert head["fallback"] is False
    assert rows[("multichip", "v")]["fallback"] is True
    # the digest itself is provenance, never a perf column
    assert ("headline", "programs_digest") not in rows
    # booleans and nested dicts are not perf columns either
    assert ("headline", "cpu_fallback") not in rows
    assert ("headline", "tail") not in rows


def test_append_ledger_backfill_updates_same_round_rows(store):
    """--resume re-merges the same round after backfilling a degraded
    stage: its rows are REPLACED, never duplicated."""
    _fill_round(store, degraded=("grid",))
    l1 = bench.append_ledger(store, None, "r07")
    assert not any(r["stage"] == "grid" for r in l1["rows"])
    # the resume backfills grid, and the headline got a better number
    store.save("grid", bench.stage_config("grid"), {"grid_ms": 42.0},
               meta={"backend": "TPU v5e", "platform": "tpu"})
    store.save("headline", bench.stage_config("headline"),
               dict(HEADLINE_DATA, e2e_p99_ms=300.0),
               meta={"backend": "TPU v5e", "platform": "tpu"})
    l2 = bench.append_ledger(store, l1, "r07")
    assert len(set(_row_keys(l2))) == len(l2["rows"]), "duplicated rows"
    grid = [r for r in l2["rows"] if r["stage"] == "grid"]
    assert [r["column"] for r in grid] == ["grid_ms"]
    head = [r for r in l2["rows"]
            if r["stage"] == "headline" and r["column"] == "e2e_p99_ms"]
    assert head[0]["value"] == 300.0, "backfill must update, not append"


def _two_round_ledger(store, second_round_data):
    """r01 at the baseline headline numbers, r02 at the given ones."""
    _fill_round(store)
    ledger = bench.append_ledger(store, None, "r01")
    store.save("headline", bench.stage_config("headline"),
               dict(HEADLINE_DATA, **second_round_data),
               meta={"backend": "TPU v5e", "platform": "tpu"})
    return bench.append_ledger(store, ledger, "r02")


def test_ledger_verdict_fires_on_seeded_slowdown(store):
    """A seeded 2x slowdown (and the matching throughput halving) on the
    same platform trips the named regression verdict — warn-only is the
    caller's contract, the verdict itself must be loud and specific."""
    ledger = _two_round_ledger(store, {
        "e2e_p99_ms": HEADLINE_DATA["e2e_p99_ms"] * 2.0,
        "pods_per_sec": HEADLINE_DATA["pods_per_sec"] / 2.0,
    })
    verdict = bench.ledger_verdict(ledger, "r02")
    assert verdict["ok"] is False
    named = {(g["stage"], g["column"]) for g in verdict["regressions"]}
    assert ("headline", "e2e_p99_ms") in named
    assert ("headline", "pods_per_sec") in named
    worst = verdict["regressions"][0]
    assert worst["worse_pct"] == pytest.approx(100.0, abs=0.2)
    assert worst["best_known"] > 0


def test_ledger_verdict_quiet_on_noise(store):
    """A 10% wiggle is measurement noise, not a regression (threshold is
    25%); a directionless column moving a lot is identity, not perf."""
    ledger = _two_round_ledger(store, {
        "e2e_p99_ms": HEADLINE_DATA["e2e_p99_ms"] * 1.10,
        "pods_per_sec": HEADLINE_DATA["pods_per_sec"] * 0.92,
        "scheduled_min": 9999,  # no direction suffix: never tripwired
    })
    verdict = bench.ledger_verdict(ledger, "r02")
    assert verdict["ok"] is True
    assert verdict["regressions"] == []


def test_ledger_verdict_compares_same_platform_only(store):
    """A CPU-fallback-grade number on a DIFFERENT platform must not be
    judged against the TPU best-known — the exact r03-r05 trap."""
    _fill_round(store)
    ledger = bench.append_ledger(store, None, "r01")
    store.save("headline", bench.stage_config("headline"),
               dict(HEADLINE_DATA, e2e_p99_ms=HEADLINE_DATA["e2e_p99_ms"] * 40),
               meta={"backend": "cpu-fallback", "platform": "cpu"})
    ledger = bench.append_ledger(store, ledger, "r02")
    assert bench.ledger_verdict(ledger, "r02")["ok"] is True


def test_ledger_verdict_excludes_fallback_rows(store):
    """Shrunk involuntary-CPU rows measure a different workload: excluded
    from both the best-known pool and the judged round."""
    _fill_round(store)
    ledger = bench.append_ledger(store, None, "r01")
    store.save("headline", bench.stage_config("headline"),
               dict(HEADLINE_DATA, e2e_p99_ms=HEADLINE_DATA["e2e_p99_ms"] * 3),
               fallback=True,
               meta={"backend": "TPU v5e", "platform": "tpu"})
    ledger = bench.append_ledger(store, ledger, "r02")
    assert bench.ledger_verdict(ledger, "r02")["ok"] is True


# ---------------------------------------------------------------------------
# probe forensics (ISSUE 18): the labeled-heartbeat phase contract and the
# verdict-file channel — the probe subprocess is faked, everything else real


def _fake_probe(label, rc=0, out="", err="", timed_out=False):
    """A _run_subprocess stand-in that behaves like a probe child reaching
    `label` before dying/succeeding."""

    def run(cmd, env, timeout_s, capture_stderr=False):
        if label:
            with open(env["BENCH_PROBE_HEARTBEAT"], "w") as f:
                f.write(label)
        return rc, out, err, timed_out

    return run


def test_probe_forensic_success_parses_platform_and_timings(monkeypatch):
    monkeypatch.setattr(bench, "_run_subprocess", _fake_probe(
        "done", rc=0,
        out="cpu TFRT_CPU\nPROBE_TIMINGS 120.5 35.0 2\n",
    ))
    ok, note, forensics = bench._probe_forensic(30)
    assert ok is True
    assert note == "cpu TFRT_CPU"  # first token = platform: the
    # _decide_backend contract the legacy note shape must keep
    assert forensics["phase"] == "done"
    assert forensics["platform"] == "cpu"
    assert forensics["import_ms"] == 120.5
    assert forensics["device_init_ms"] == 35.0
    assert forensics["device_count"] == 2
    assert forensics["timed_out"] is False


def test_probe_forensic_timeout_names_init_phase(monkeypatch):
    """The whole point: a wedged TPU probe says WHERE it wedged instead
    of just 'timeout' — the phase label the child last marked."""
    monkeypatch.setattr(bench, "_run_subprocess", _fake_probe(
        "device-init", rc=None, err="libtpu: waiting for TPU system\n",
        timed_out=True,
    ))
    ok, note, forensics = bench._probe_forensic(60)
    assert ok is False
    assert "(in device-init)" in note
    assert forensics["phase"] == "device-init"
    assert forensics["timed_out"] is True
    assert "libtpu" in forensics["stderr_tail"]


def test_probe_forensic_no_mark_reads_as_spawn(monkeypatch):
    monkeypatch.setattr(bench, "_run_subprocess", _fake_probe(
        "", rc=None, timed_out=True,
    ))
    _ok, note, forensics = bench._probe_forensic(10)
    assert forensics["phase"] == "spawn"
    assert "(in spawn)" in note


def test_probe_forensic_stderr_tail_is_bounded_and_redacted(monkeypatch):
    secret = "hunter2-very-secret-token"
    monkeypatch.setenv("KCT_TEST_SECRET_TOKEN", secret)
    monkeypatch.setattr(bench, "_run_subprocess", _fake_probe(
        "import", rc=1,
        err=("x" * (bench.PROBE_FORENSIC_TAIL * 2))
        + f"\nauth failed with {secret}\nfatal: no backend\n",
    ))
    _ok, note, forensics = bench._probe_forensic(10)
    assert note == "fatal: no backend"  # legacy last-stderr-line note
    tail = forensics["stderr_tail"]
    assert len(tail) <= bench.PROBE_FORENSIC_TAIL + 64
    assert secret not in tail, "env values must be redacted from the tail"


def test_read_verdict_forensics_survives_ttl_expiry(tmp_path):
    """read_verdict treats a stale verdict as no verdict (gating); the
    forensic record must still be readable — it's evidence, not a gate."""
    path = str(tmp_path / "health.json")
    record = {"phase": "device-init", "timed_out": True, "rc": None}
    supervise.write_verdict(path, False, "probe timeout after 60s",
                            ttl_s=0.0, extra={"probe_forensics": record})
    import time as _t

    _t.sleep(0.02)
    assert supervise.read_verdict(path) is None, "stale must not gate"
    got = bench._read_verdict_forensics(path)
    assert got == record
    assert bench._read_verdict_forensics(str(tmp_path / "missing.json")) is None


def test_probe_script_marks_real_phases(tmp_path):
    """The actual probe child script (minus the jax import — replaced by a
    stub module) drives the real heartbeat-file contract end to end."""
    import subprocess
    import sys as _sys

    hb = str(tmp_path / "hb")
    stub_dir = tmp_path / "stub"
    stub_dir.mkdir()
    (stub_dir / "jax.py").write_text(
        "class _D:\n"
        "    platform = 'cpu'\n"
        "    device_kind = 'stub'\n"
        "def devices():\n"
        "    return [_D()]\n"
    )
    env = {**__import__("os").environ, "BENCH_PROBE_HEARTBEAT": hb,
           "PYTHONPATH": str(stub_dir)}
    out = subprocess.run(
        [_sys.executable, "-c", bench._PROBE_SCRIPT], env=env,
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    assert supervise.Heartbeat(hb).read_label() == "done"
    lines = out.stdout.splitlines()
    assert lines[0] == "cpu stub"
    assert lines[1].startswith("PROBE_TIMINGS ")


def test_timeline_tolerates_missing_meta_and_empty_store(store):
    # empty store: a valid, empty-ish timeline (orchestrator row only)
    tl = bench.build_timeline(store)
    assert [e["name"] for e in tl["traceEvents"]] == ["process_name"]
    # artifacts with no timing meta (old rounds) still merge
    for name in bench.STAGE_NAMES:
        store.save(name, bench.stage_config(name), {"v": 1})
    tl = bench.build_timeline(store)
    assert tl["otherData"]["stages"]["headline"] == "ok"
    assert not any(
        e["name"].startswith("bench.stage.") for e in tl["traceEvents"]
    )
