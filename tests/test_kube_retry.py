"""ApiServerKubeClient transport retries: exponential backoff + jitter on
transient failures (5xx / 429 / timeout / connection reset), Retry-After
honored, conflicts (409) and other 4xx never retried."""
import random

import pytest

from karpenter_core_tpu import chaos
from karpenter_core_tpu.kube.apiserver import (
    KUBE_TRANSPORT_RETRIES,
    ApiServerKubeClient,
)
from karpenter_core_tpu.kube.client import ConflictError
from karpenter_core_tpu.testing import make_pod


@pytest.fixture(autouse=True)
def _clean_registry():
    chaos.reset()
    yield
    chaos.reset()


class ScriptedTransport:
    """Yields scripted outcomes per call: an Exception instance (raised), or
    a (status, body[, headers]) tuple; the last entry repeats."""

    def __init__(self, *script):
        self.script = list(script)
        self.calls = []

    def __call__(self, method, path, body=None, params=None, stream=False,
                 timeout=30.0):
        self.calls.append((method, path))
        outcome = self.script[min(len(self.calls) - 1, len(self.script) - 1)]
        if isinstance(outcome, BaseException):
            raise outcome
        return outcome


POD_OK = (
    200,
    '{"metadata": {"name": "p", "namespace": "default", '
    '"resourceVersion": "3"}, "spec": {}, "status": {}}',
)


def client_for(transport, **kw):
    kw.setdefault("retry_base", 0.001)
    kw.setdefault("retry_max", 0.01)
    kw.setdefault("rng", random.Random(7))
    return ApiServerKubeClient(transport, **kw)


def test_connection_reset_is_retried():
    transport = ScriptedTransport(
        ConnectionResetError("peer reset"), ConnectionResetError("again"), POD_OK
    )
    before = KUBE_TRANSPORT_RETRIES.get({"method": "GET"})
    client = client_for(transport)
    pod = client.get("Pod", "default", "p")
    assert pod is not None and pod.metadata.name == "p"
    assert len(transport.calls) == 3
    assert KUBE_TRANSPORT_RETRIES.get({"method": "GET"}) == before + 2


def test_5xx_is_retried_until_success():
    transport = ScriptedTransport((503, "unavailable"), (502, "bad gw"), POD_OK)
    client = client_for(transport)
    assert client.get("Pod", "default", "p") is not None
    assert len(transport.calls) == 3


def test_retry_after_header_is_honored():
    waits = []

    class Recording(ApiServerKubeClient):
        def _backoff(self, attempt, retry_after):
            waits.append(retry_after)
            return 0.0

    transport = ScriptedTransport(
        (429, "slow down", {"Retry-After": "7"}), POD_OK
    )
    client = Recording(transport)
    assert client.get("Pod", "default", "p") is not None
    assert waits == ["7"]
    # and the real backoff caps a parseable Retry-After at retry_max
    real = client_for(ScriptedTransport(POD_OK), retry_max=2.0)
    assert real._backoff(0, "7") == 2.0
    assert real._backoff(0, "1.5") == 1.5


def test_write_verbs_do_not_retry_ambiguous_statuses():
    """A 502/504 on a POST can arrive AFTER a gateway-fronted apiserver
    committed the write: replaying would turn success into AlreadyExists.
    Writes only retry the not-applied statuses (429/503); GET keeps the
    full transient set."""
    transport = ScriptedTransport((502, "bad gateway"))
    client = client_for(transport)
    with pytest.raises(RuntimeError, match="apiserver 502"):
        client.create(make_pod(name="p"))
    assert len(transport.calls) == 1
    # 503 is a pre-processing rejection: retried even for writes
    transport2 = ScriptedTransport(
        (503, "overloaded"),
        (201, '{"metadata": {"name": "p", "namespace": "default", '
              '"resourceVersion": "1"}, "spec": {}, "status": {}}'),
    )
    client2 = client_for(transport2)
    assert client2.create(make_pod(name="p")) is not None
    assert len(transport2.calls) == 2
    # ambiguous connection failures: never replayed for writes
    transport3 = ScriptedTransport(ConnectionResetError("mid-flight"))
    client3 = client_for(transport3)
    with pytest.raises(ConnectionResetError):
        client3.create(make_pod(name="p"))
    assert len(transport3.calls) == 1


def test_conflict_is_never_retried():
    transport = ScriptedTransport((409, '{"reason": "Conflict"}'))
    client = client_for(transport)
    pod = make_pod(name="p")
    pod.metadata.resource_version = 1
    with pytest.raises(ConflictError):
        client.update(pod)
    assert len(transport.calls) == 1, "409 must return to the caller untouched"


def test_plain_4xx_is_not_retried():
    transport = ScriptedTransport((403, "forbidden"))
    client = client_for(transport)
    with pytest.raises(RuntimeError, match="apiserver 403"):
        client.get("Pod", "default", "p")
    assert len(transport.calls) == 1


def test_retries_exhaust_and_raise():
    transport = ScriptedTransport(TimeoutError("t"))
    client = client_for(transport, retry_attempts=3)
    with pytest.raises(TimeoutError):
        client.get("Pod", "default", "p")
    assert len(transport.calls) == 4  # 1 initial + 3 retries


def test_backoff_is_jittered_and_bounded():
    client = client_for(ScriptedTransport(POD_OK), retry_base=0.1, retry_max=2.0,
                        rng=random.Random(3))
    samples = [client._backoff(a, None) for a in range(5) for _ in range(20)]
    assert all(0.0 <= s <= 2.0 for s in samples)
    assert len(set(samples)) > 10, "backoff must be jittered, not a fixed ladder"


def test_eviction_pdb_429_is_not_retried():
    """Eviction's 429 is a PodDisruptionBudget verdict, not a rate limit:
    the eviction queue requeues it; the transport layer must not burn
    seconds replaying it."""
    from karpenter_core_tpu.kube.client import EvictionBlockedError

    transport = ScriptedTransport((429, "budget exhausted"))
    client = client_for(transport)
    with pytest.raises(EvictionBlockedError):
        client.evict("default", "p")
    assert len(transport.calls) == 1


def test_chaos_transport_fault_rides_the_retry_loop():
    """An injected kube.transport fault inside _request is classified and
    retried exactly like a wire failure."""
    fault = chaos.arm(chaos.KUBE_TRANSPORT, error="conn", times=2)
    client = client_for(ScriptedTransport(POD_OK))
    assert client.get("Pod", "default", "p") is not None
    assert fault.injected == 2
