"""Randomized host-vs-device differential fuzz.

Random workloads (requests, selectors, taints/tolerations, zonal spread,
host ports, existing nodes) solved by both the host GreedySolver (the
reference-semantics oracle, scheduler.go:96-133) and the TPU kernel path.
The equivalence bar (SURVEY.md §7e): all constraints satisfied and the
device result no worse than the host oracle — greedy order-dependence
allows different but equally-valid placements, so placements are not
compared bit-for-bit.

Label values draw from a fixed vocabulary and every value is anchored by
one pod per seed, keeping the dictionary geometry constant so the three
seeds share one compiled device program.
"""
import numpy as np
import pytest

from karpenter_core_tpu.api.labels import (
    LABEL_CAPACITY_TYPE,
    LABEL_NODE_INITIALIZED,
    PROVISIONER_NAME_LABEL_KEY,
)
from karpenter_core_tpu.cloudprovider import fake
from karpenter_core_tpu.kube.objects import (
    LABEL_INSTANCE_TYPE_STABLE,
    LABEL_TOPOLOGY_ZONE,
    LabelSelector,
    Taint,
    Toleration,
    TopologySpreadConstraint,
)
from karpenter_core_tpu.solver.tpu_solver import GreedySolver, TPUSolver
from karpenter_core_tpu.state.node import StateNode
from karpenter_core_tpu.testing import make_node, make_pod, make_provisioner

from tests.test_tpu_solver import validate_machines

ZONES = ["test-zone-1", "test-zone-2", "test-zone-3"]
APPS = ["a", "b", "c", "d"]


def _workload(rng: np.random.Generator, universe):
    zonal = TopologySpreadConstraint(
        max_skew=1,
        topology_key=LABEL_TOPOLOGY_ZONE,
        when_unsatisfiable="DoNotSchedule",
        label_selector=LabelSelector(match_labels={"app": "spread"}),
    )
    pods = []
    # anchors: one pod per vocabulary value so the dictionary (and the
    # compiled geometry) is identical across seeds
    for z in ZONES:
        pods.append(make_pod(requests={"cpu": "0.1"}, node_selector={LABEL_TOPOLOGY_ZONE: z}))
    for app in APPS:
        pods.append(make_pod(labels={"app": app}, requests={"cpu": "0.1"}))
    pods.append(make_pod(labels={"app": "spread"}, requests={"cpu": "0.1"}, topology_spread=[zonal]))
    pods.append(make_pod(requests={"cpu": "0.1"}, host_ports=[9000]))
    pods.append(
        make_pod(
            requests={"cpu": "0.1"},
            tolerations=[Toleration(key="dedicated", operator="Exists")],
        )
    )
    while len(pods) < 72:
        kind = int(rng.integers(0, 6))
        cpu = float(rng.choice([0.25, 0.5, 1.0, 2.0]))
        mem = str(int(rng.choice([1, 2, 4]))) + "Gi"
        if kind == 0:
            pods.append(
                make_pod(
                    requests={"cpu": str(cpu)},
                    node_selector={LABEL_TOPOLOGY_ZONE: str(rng.choice(ZONES))},
                )
            )
        elif kind == 1:
            pods.append(
                make_pod(
                    labels={"app": "spread"},
                    requests={"cpu": str(cpu)},
                    topology_spread=[zonal],
                )
            )
        elif kind == 2:
            pods.append(make_pod(requests={"cpu": str(cpu)}, host_ports=[9000]))
        elif kind == 3:
            pods.append(
                make_pod(
                    requests={"cpu": str(cpu), "memory": mem},
                    tolerations=[Toleration(key="dedicated", operator="Exists")],
                )
            )
        else:
            pods.append(
                make_pod(
                    labels={"app": str(rng.choice(APPS))},
                    requests={"cpu": str(cpu), "memory": mem},
                )
            )
    order = rng.permutation(len(pods))
    pods = [pods[i] for i in order]

    nodes = []
    for e in range(6):
        it = universe[e % len(universe)]
        nodes.append(
            StateNode(
                node=make_node(
                    name=f"fuzz-node-{e}",
                    labels={
                        PROVISIONER_NAME_LABEL_KEY: "default",
                        LABEL_NODE_INITIALIZED: "true",
                        LABEL_INSTANCE_TYPE_STABLE: it.name,
                        LABEL_CAPACITY_TYPE: "on-demand",
                        LABEL_TOPOLOGY_ZONE: ZONES[e % 3],
                    },
                    capacity={k: str(v) for k, v in it.capacity.items()},
                )
            )
        )
    provisioners = [
        make_provisioner(name="default"),
        make_provisioner(
            name="tainted",
            weight=10,
            taints=[Taint(key="dedicated", value="x", effect="NoSchedule")],
        ),
    ]
    its = {"default": universe, "tainted": universe}
    return pods, provisioners, its, nodes


def _check_invariants(res, pods):
    from collections import Counter

    from karpenter_core_tpu.scheduling import taints as taints_mod
    from karpenter_core_tpu.scheduling.requirements import Requirements
    from karpenter_core_tpu.utils import resources as resources_util

    validate_machines(res)
    # exactly-once accounting: a Counter catches double placement (machine
    # AND existing node), which id-sets would silently collapse
    placements = Counter(id(p) for m in res.new_machines for p in m.pods)
    placements.update(id(p) for _n, ps in res.existing_assignments for p in ps)
    assert not [c for c in placements.values() if c > 1], "pod placed twice"
    failed = {id(p) for p in res.failed_pods}
    assert failed.isdisjoint(placements)
    assert len(placements) + len(failed) == len(pods), "every pod accounted once"

    # existing-node assignments satisfy the same constraint algebra the
    # machines do: capacity, node selector/affinity, taints
    for node, ps in res.existing_assignments:
        total = resources_util.merge(
            *[resources_util.requests_for_pods(p) for p in ps]
        )
        assert resources_util.fits(total, node.available()), (
            f"existing node {node.name()} overcommitted: {total}"
        )
        node_reqs = Requirements.from_labels(node.labels())
        for p in ps:
            assert taints_mod.tolerates(node.taints(), p) is None
            assert node_reqs.compatible(Requirements.from_pod(p)) is None, (
                f"pod selector incompatible with existing node {node.name()}"
            )

    # zonal topology spread (DoNotSchedule, max_skew=1): count app=spread
    # pods per zone over nodes that match the constraint's domains
    zone_counts = {z: 0 for z in ZONES}
    for m in res.new_machines:
        if LABEL_TOPOLOGY_ZONE not in m.requirements:
            continue
        zs = sorted(m.requirements[LABEL_TOPOLOGY_ZONE].values)
        n_spread = sum(1 for p in m.pods if p.metadata.labels.get("app") == "spread")
        if n_spread:
            assert len(zs) == 1, "spread owner machine must pin one zone"
            zone_counts[zs[0]] += n_spread
    for node, ps in res.existing_assignments:
        z = node.labels().get(LABEL_TOPOLOGY_ZONE)
        zone_counts[z] += sum(
            1 for p in ps if p.metadata.labels.get("app") == "spread"
        )
    counts = list(zone_counts.values())
    if sum(counts):
        assert max(counts) - min(counts) <= 1, f"zonal skew violated: {zone_counts}"

    # host-port exclusivity: one port-9000 pod per node (machine or existing)
    for m in res.new_machines:
        n_ports = sum(
            1
            for p in m.pods
            for c in p.spec.containers
            for port in c.ports
            if port.host_port
        )
        assert n_ports <= 1, "two hostPort pods co-located on a machine"
    for _node, ps in res.existing_assignments:
        n_ports = sum(
            1
            for p in ps
            for c in p.spec.containers
            for port in c.ports
            if port.host_port
        )
        assert n_ports <= 1, "two hostPort pods co-located on an existing node"


@pytest.mark.parametrize("seed", [11, 23, 47])
def test_fuzz_host_vs_device(seed):
    rng = np.random.default_rng(seed)
    universe = fake.instance_types(8)
    pods, provisioners, its, nodes = _workload(rng, universe)
    host = GreedySolver().solve(pods, provisioners, its, state_nodes=nodes)
    tpu = TPUSolver(max_nodes=96).solve(pods, provisioners, its, state_nodes=nodes)
    _check_invariants(tpu, pods)
    assert len(tpu.failed_pods) <= len(host.failed_pods), (
        f"device failed {len(tpu.failed_pods)} vs host {len(host.failed_pods)}: "
        f"{[p.metadata.labels for p in tpu.failed_pods[:5]]}"
    )
    # §7e equivalence bar with one node of slack: the device packs
    # spec-equivalence items as replica groups where the host interleaves
    # single pods, so under hostPort exclusivity (one port pod per node)
    # the two greedy orders can split the same workload one node apart
    # (seed 23 does). A targeted check confirms port pods DO bulk-fill
    # onto existing nodes; curated tests (test_device_semantics,
    # test_tpu_solver) hold the strict <= bar on non-adversarial mixes.
    assert len(tpu.new_machines) <= len(host.new_machines) + 1


_SHARDED = {}


@pytest.mark.parametrize("seed", [11, 23, 47])
def test_fuzz_single_vs_sharded(seed, monkeypatch):
    """The SAME random workloads through the production multi-chip path
    (ShardedSolver over the 8-device mesh) vs the single-device solver.
    ISSUE 8 bar: the GSPMD mesh program is the single-device program with
    sharding constraints, so placements are BYTE-IDENTICAL
    (flightrec-canonical) — strictly stronger than the old per-shard
    equivalence bound. The routing floor is zeroed so these 72-pod
    batches exercise the mesh program rather than the small-batch
    single-device fast path (which is trivially identical)."""
    import jax
    from jax.sharding import Mesh

    from karpenter_core_tpu.obs.flightrec import (
        canonical_placements,
        placements_json,
    )
    from karpenter_core_tpu.parallel import sharded as sharded_mod
    from karpenter_core_tpu.parallel.sharded import ShardedSolver

    monkeypatch.setattr(sharded_mod, "MIN_SPLIT_REPLICAS_PER_SHARD", 0)
    rng = np.random.default_rng(seed)
    universe = fake.instance_types(8)
    pods, provisioners, its, nodes = _workload(rng, universe)
    single = TPUSolver(max_nodes=96).solve(
        pods, provisioners, its,
        state_nodes=[n.deep_copy() for n in nodes],
    )
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2), ("dp", "tp"))
    # one solver across the seeds: the anchored vocabulary keeps the
    # geometry constant, so the mesh program compiles once
    solver = _SHARDED.setdefault("s", ShardedSolver(mesh, max_nodes=96))
    sharded = solver.solve(
        pods, provisioners, its,
        state_nodes=[n.deep_copy() for n in nodes],
    )
    assert solver.last_path == "mesh"
    _check_invariants(sharded, pods)
    assert placements_json(canonical_placements(sharded)) == placements_json(
        canonical_placements(single)
    ), (
        f"mesh placements diverged: {len(sharded.new_machines)} machines / "
        f"{len(sharded.failed_pods)} failed vs single-device "
        f"{len(single.new_machines)} / {len(single.failed_pods)}"
    )


_SEGMENTED = {}


@pytest.mark.parametrize("seed", [11, 23, 47])
def test_fuzz_sequential_vs_segmented(seed):
    """The SAME random workloads through KCT_PACK_SCAN=segmented vs the
    sequential scan (ISSUE 14 bar): placements BYTE-IDENTICAL
    (flightrec-canonical) on every seed. The G1 mix carries topology
    spread and hostPorts, so most seeds exercise the structural
    sequential fallback — the contract is identity either way, the fixup
    pass being the sequential kernel itself."""
    from karpenter_core_tpu.testing import solve_scan_parity

    rng = np.random.default_rng(seed)
    universe = fake.instance_types(8)
    pods, provisioners, its, nodes = _workload(rng, universe)
    _seq, seg = solve_scan_parity(
        _SEGMENTED, pods, provisioners, its, nodes=nodes
    )
    _check_invariants(seg, pods)
