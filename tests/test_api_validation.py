"""API validation/defaulting + webhooks tests.

Mirrors reference pkg/apis/v1alpha5/suite_test.go (validation specs for
TTLs, consolidation exclusivity, provider-xor-providerRef, labels, taints,
requirements, kubelet configuration) and pkg/webhooks behavior.
"""
import pytest

from karpenter_core_tpu.api import labels as api_labels
from karpenter_core_tpu.api.provisioner import (
    Consolidation,
    KubeletConfiguration,
    ProviderRef,
)
from karpenter_core_tpu.api.validation import (
    ValidationError,
    is_qualified_name,
    is_valid_label_value,
    validate_or_raise,
    validate_provisioner,
    validate_requirement,
)
from karpenter_core_tpu.kube.objects import (
    LABEL_TOPOLOGY_ZONE,
    ConfigMap,
    NodeSelectorRequirement,
    ObjectMeta,
    Taint,
)
from karpenter_core_tpu.testing import make_provisioner
from karpenter_core_tpu.webhooks import AdmissionWebhooks, install
from karpenter_core_tpu.kube.client import InMemoryKubeClient


def valid_provisioner(**kwargs):
    return make_provisioner(**kwargs)


def errs(p):
    return validate_provisioner(p)


# -- basic shape ------------------------------------------------------------


def test_valid_provisioner_passes():
    assert errs(valid_provisioner()) == []


def test_name_required_and_dns1123():
    p = valid_provisioner()
    p.metadata.name = ""
    assert any("name is required" in e for e in errs(p))
    p.metadata.name = "Not_A_DNS_Label"
    assert any("DNS-1123" in e for e in errs(p))
    p.metadata.name = "x" * 64
    assert any("DNS-1123" in e for e in errs(p))


def test_ttls_cannot_be_negative():
    p = valid_provisioner()
    p.spec.ttl_seconds_until_expired = -1
    assert any("ttlSecondsUntilExpired" in e for e in errs(p))
    p = valid_provisioner()
    p.spec.ttl_seconds_after_empty = -1
    assert any("ttlSecondsAfterEmpty" in e for e in errs(p))


def test_consolidation_and_empty_ttl_mutually_exclusive():
    p = valid_provisioner(ttl_seconds_after_empty=30)
    p.spec.consolidation = Consolidation(enabled=True)
    assert any("ttlSecondsAfterEmpty, consolidation.enabled" in e for e in errs(p))
    # disabled consolidation is fine
    p.spec.consolidation = Consolidation(enabled=False)
    assert errs(p) == []


def test_provider_xor_provider_ref():
    p = valid_provisioner()
    p.spec.provider = {"x": 1}
    p.spec.provider_ref = ProviderRef(kind="NodeTemplate", name="t")
    assert any("got both" in e for e in errs(p))
    p.spec.provider = None
    p.spec.provider_ref = None
    assert any("got neither" in e for e in errs(p))
    p.spec.provider_ref = ProviderRef(kind="NodeTemplate", name="t")
    assert errs(p) == []


# -- labels -----------------------------------------------------------------


def test_restricted_labels_rejected():
    p = valid_provisioner(labels={api_labels.PROVISIONER_NAME_LABEL_KEY: "x"})
    assert any("restricted" in e for e in errs(p))
    p = valid_provisioner(labels={"kubernetes.io/custom": "x"})
    assert any("restricted" in e for e in errs(p))


def test_label_domain_exceptions_allowed():
    assert errs(valid_provisioner(labels={"kops.k8s.io/instancegroup": "x"})) == []
    assert errs(valid_provisioner(labels={"node.kubernetes.io/custom": "x"})) == []
    assert errs(valid_provisioner(labels={"subdomain.kops.k8s.io/instancegroup": "x"})) != []


def test_well_known_labels_allowed():
    assert errs(valid_provisioner(labels={LABEL_TOPOLOGY_ZONE: "zone-1"})) == []


def test_invalid_label_syntax():
    p = valid_provisioner(labels={"has a space": "x"})
    assert errs(p) != []
    p = valid_provisioner(labels={"ok": "bad value!"})
    assert errs(p) != []
    p = valid_provisioner(labels={"ok": "x" * 64})
    assert errs(p) != []


# -- taints -----------------------------------------------------------------


def test_taint_validation():
    p = valid_provisioner(taints=[Taint(key="", value="", effect="NoSchedule")])
    assert any("taint key is required" in e for e in errs(p))
    p = valid_provisioner(taints=[Taint(key="k", value="v", effect="Bogus")])
    assert any("invalid effect" in e for e in errs(p))
    p = valid_provisioner(taints=[Taint(key="k", value="bad value!", effect="NoSchedule")])
    assert errs(p) != []


def test_duplicate_taint_key_effect_rejected_across_startup():
    t = Taint(key="dedicated", value="a", effect="NoSchedule")
    p = valid_provisioner(taints=[t], startup_taints=[Taint(key="dedicated", value="b", effect="NoSchedule")])
    assert any("duplicate taint" in e for e in errs(p))
    # same key, different effect is fine
    p = valid_provisioner(
        taints=[t], startup_taints=[Taint(key="dedicated", value="b", effect="NoExecute")]
    )
    assert errs(p) == []


# -- requirements -----------------------------------------------------------


def test_requirement_operator_support():
    for op in ("In", "NotIn", "Exists", "DoesNotExist"):
        req = NodeSelectorRequirement(key="custom", operator=op, values=["a"] if op in ("In", "NotIn") else [])
        assert validate_requirement(req) == []
    bad = NodeSelectorRequirement(key="custom", operator="Unknown", values=[])
    assert any("unsupported operator" in e for e in validate_requirement(bad))


def test_requirement_in_needs_values():
    req = NodeSelectorRequirement(key="custom", operator="In", values=[])
    assert any("must have a value" in e for e in validate_requirement(req))


def test_requirement_gt_lt_single_positive_integer():
    for op in ("Gt", "Lt"):
        assert validate_requirement(NodeSelectorRequirement(key="c", operator=op, values=["5"])) == []
        for values in ([], ["1", "2"], ["-3"], ["x"]):
            req = NodeSelectorRequirement(key="c", operator=op, values=values)
            assert any("single positive integer" in e for e in validate_requirement(req))


def test_requirement_restricted_key():
    req = NodeSelectorRequirement(key="karpenter.sh/custom", operator="Exists", values=[])
    assert any("restricted" in e for e in validate_requirement(req))
    p = valid_provisioner(
        requirements=[
            NodeSelectorRequirement(
                key=api_labels.PROVISIONER_NAME_LABEL_KEY, operator="In", values=["x"]
            )
        ]
    )
    assert any("restricted" in e for e in errs(p))


def test_requirement_normalized_key_accepted():
    # beta zone label normalizes to the stable well-known key
    req = NodeSelectorRequirement(
        key="failure-domain.beta.kubernetes.io/zone", operator="In", values=["z1"]
    )
    assert validate_requirement(req) == []


# -- kubelet configuration --------------------------------------------------


def test_kubelet_eviction_signal_keys():
    kc = KubeletConfiguration(eviction_hard={"memory.available": "5%"})
    p = valid_provisioner()
    p.spec.kubelet_configuration = kc
    assert errs(p) == []
    kc.eviction_hard = {"bogus.signal": "5%"}
    assert any("invalid key name bogus.signal" in e for e in errs(p))


def test_kubelet_eviction_threshold_values():
    p = valid_provisioner()
    for bad in ("-5%", "110%", "x%"):
        p.spec.kubelet_configuration = KubeletConfiguration(
            eviction_hard={"memory.available": bad}
        )
        assert errs(p) != [], bad
    p.spec.kubelet_configuration = KubeletConfiguration(
        eviction_hard={"memory.available": "1Gi"}
    )
    assert errs(p) == []


def test_kubelet_eviction_soft_pairs():
    p = valid_provisioner()
    p.spec.kubelet_configuration = KubeletConfiguration(
        eviction_soft={"memory.available": "5%"}
    )
    assert any("matching evictionSoftGracePeriod" in e for e in errs(p))
    p.spec.kubelet_configuration = KubeletConfiguration(
        eviction_soft_grace_period={"memory.available": "1m"}
    )
    assert any("matching evictionSoft threshold" in e for e in errs(p))
    p.spec.kubelet_configuration = KubeletConfiguration(
        eviction_soft={"memory.available": "5%"},
        eviction_soft_grace_period={"memory.available": "1m"},
    )
    assert errs(p) == []


def test_kubelet_reserved_resources():
    p = valid_provisioner()
    p.spec.kubelet_configuration = KubeletConfiguration(kube_reserved={"cpu": "1"})
    assert errs(p) == []
    p.spec.kubelet_configuration = KubeletConfiguration(kube_reserved={"gpus": "1"})
    assert any("invalid key name gpus" in e for e in errs(p))
    p.spec.kubelet_configuration = KubeletConfiguration(system_reserved={"cpu": "-1"})
    assert any("negative" in e for e in errs(p))


def test_kubelet_image_gc_thresholds():
    p = valid_provisioner()
    p.spec.kubelet_configuration = KubeletConfiguration(
        image_gc_high_threshold_percent=50, image_gc_low_threshold_percent=60
    )
    assert any("imageGCHighThresholdPercent" in e for e in errs(p))
    p.spec.kubelet_configuration = KubeletConfiguration(
        image_gc_high_threshold_percent=60, image_gc_low_threshold_percent=50
    )
    assert errs(p) == []


def test_kubelet_negative_counts():
    p = valid_provisioner()
    p.spec.kubelet_configuration = KubeletConfiguration(max_pods=-1)
    assert any("maxPods" in e for e in errs(p))
    p.spec.kubelet_configuration = KubeletConfiguration(pods_per_core=-1)
    assert any("podsPerCore" in e for e in errs(p))


# -- name syntax helpers ----------------------------------------------------


def test_qualified_name_rules():
    assert is_qualified_name("simple") == []
    assert is_qualified_name("domain.io/name") == []
    assert is_qualified_name("") != []
    assert is_qualified_name("a/b/c") != []
    assert is_qualified_name("UPPER.domain/x") != []
    assert is_qualified_name("domain.io/" + "x" * 64) != []


def test_label_value_rules():
    assert is_valid_label_value("") == []
    assert is_valid_label_value("ok-value_1.x") == []
    assert is_valid_label_value("-leading") != []
    assert is_valid_label_value("x" * 64) != []


# -- webhooks ---------------------------------------------------------------


def test_webhook_install_rejects_invalid_writes():
    client = InMemoryKubeClient()
    install(client)
    good = valid_provisioner()
    client.create(good)
    bad = valid_provisioner()
    bad.spec.ttl_seconds_after_empty = -5
    with pytest.raises(ValidationError):
        client.create(bad)
    # updates are validated too
    good.spec.ttl_seconds_until_expired = -1
    with pytest.raises(ValidationError):
        client.update(good)


def test_webhook_validates_settings_config_map():
    client = InMemoryKubeClient()
    install(client)
    cm = ConfigMap(
        metadata=ObjectMeta(name="karpenter-global-settings", namespace="karpenter"),
        data={"batchMaxDuration": "10s"},
    )
    client.create(cm)
    bad = ConfigMap(
        metadata=ObjectMeta(name="karpenter-global-settings", namespace="karpenter"),
        data={"batchMaxDuration": "not-a-duration"},
    )
    bad.metadata.name = "karpenter-global-settings"
    with pytest.raises(ValidationError):
        client.update(bad)
    # other config maps are not validated
    other = ConfigMap(metadata=ObjectMeta(name="other", namespace="karpenter"), data={"x": "y"})
    client.create(other)


def test_validate_or_raise_dispatch():
    validate_or_raise(valid_provisioner())
    bad = valid_provisioner()
    bad.spec.provider = None
    with pytest.raises(ValidationError):
        validate_or_raise(bad)
