"""Port of reference pkg/controllers/provisioning/suite_test.go — the
daemonset-overhead filtering, node annotation/label propagation, machine
request content, and storage-zone specs the condensed tests don't pin.
Cited line numbers refer to
/root/reference/pkg/controllers/provisioning/suite_test.go.
"""
import pytest

from karpenter_core_tpu.api import labels as api_labels
from karpenter_core_tpu.cloudprovider import fake
from karpenter_core_tpu.kube.objects import (
    LABEL_INSTANCE_TYPE_STABLE,
    LABEL_TOPOLOGY_ZONE,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    Taint,
    Toleration,
)
from karpenter_core_tpu.testing import (
    make_daemonset,
    make_pod,
    make_provisioner,
    make_pv,
    make_pvc,
    make_storage_class,
    pvc_volume,
)
from karpenter_core_tpu.testing.expectations import Env

LADDER = fake.instance_types(10)  # fake-it-i: (i+1) cpu


@pytest.fixture()
def env():
    return Env(universe=LADDER)


def req(key, op, *values):
    return NodeSelectorRequirement(key=key, operator=op, values=list(values))


def chosen_cpu(env, pod):
    node = env.expect_scheduled(pod)
    name = node.metadata.labels[LABEL_INSTANCE_TYPE_STABLE]
    return next(it.capacity["cpu"] for it in env.universe if it.name == name)


def test_ignores_deleting_provisioners(env):
    """suite_test.go:111-121."""
    prov = make_provisioner(name="default")
    env.expect_applied(prov)
    prov.metadata.deletion_timestamp = env.clock()
    env.kube.update(prov)
    pod = make_pod(requests={"cpu": "1"})
    env.expect_provisioned(pod)
    env.expect_not_scheduled(pod)
    assert not env.cloud_provider.create_calls


def test_daemonset_overhead_counted(env):
    """suite_test.go:370-387 — a matching daemonset's requests inflate the
    chosen node size."""
    env.expect_applied(make_provisioner(name="default"),
                       make_daemonset(requests={"cpu": "1"}))
    pod = make_pod(requests={"cpu": "1"})
    env.expect_provisioned(pod)
    # pod(1) + daemon(1) + 0.1 overhead -> exactly the 3-cpu rung
    assert chosen_cpu(env, pod) == 3


def test_daemonset_without_matching_toleration_ignored(env):
    """suite_test.go:493-512 — daemonsets that can't tolerate the
    provisioner's taints add no overhead."""
    env.expect_applied(
        make_provisioner(name="default",
                         taints=[Taint(key="foo", value="bar", effect="NoSchedule")]),
        make_daemonset(requests={"cpu": "1"}),
    )
    pod = make_pod(requests={"cpu": "1"},
                   tolerations=[Toleration(operator="Exists")])
    env.expect_provisioned(pod)
    assert chosen_cpu(env, pod) == 2, "no daemon overhead counted"


def test_daemonset_with_incompatible_selector_ignored(env):
    """suite_test.go:513-530."""
    env.expect_applied(
        make_provisioner(name="default"),
        make_daemonset(requests={"cpu": "1"},
                       node_selector={"node": "invalid"}),
    )
    pod = make_pod(requests={"cpu": "1"})
    env.expect_provisioned(pod)
    assert chosen_cpu(env, pod) == 2


def test_daemonset_with_notin_unspecified_key_counted(env):
    """suite_test.go:531-551 — NotIn over an unspecified key matches, so the
    daemonset counts."""
    env.expect_applied(
        make_provisioner(name="default"),
        make_daemonset(
            requests={"cpu": "1"},
            node_affinity_required=[
                NodeSelectorTerm(match_expressions=[req("foo", "NotIn", "bar")])
            ],
        ),
    )
    pod = make_pod(
        requests={"cpu": "1"},
        node_affinity_required=[
            NodeSelectorTerm(
                match_expressions=[req(LABEL_TOPOLOGY_ZONE, "In", "test-zone-2")]
            )
        ],
    )
    env.expect_provisioned(pod)
    assert chosen_cpu(env, pod) == 3


def test_daemonset_with_matching_toleration_counted(env):
    """suite_test.go:493-512 inverse — a daemonset that DOES tolerate the
    provisioner's taints adds its overhead."""
    env.expect_applied(
        make_provisioner(name="default",
                         taints=[Taint(key="foo", value="bar", effect="NoSchedule")]),
        make_daemonset(requests={"cpu": "1"},
                       tolerations=[Toleration(operator="Exists")]),
    )
    pod = make_pod(requests={"cpu": "1"},
                   tolerations=[Toleration(operator="Exists")])
    env.expect_provisioned(pod)
    assert chosen_cpu(env, pod) == 3


def test_provisioner_annotations_propagate_to_nodes(env):
    """suite_test.go:552-563."""
    env.expect_applied(
        make_provisioner(
            name="default",
            annotations={api_labels.DO_NOT_CONSOLIDATE_NODE_ANNOTATION_KEY: "true"},
        )
    )
    pod = make_pod(requests={"cpu": "1"})
    env.expect_provisioned(pod)
    node = env.expect_scheduled(pod)
    assert node.metadata.annotations.get(
        api_labels.DO_NOT_CONSOLIDATE_NODE_ANNOTATION_KEY
    ) == "true"


def test_provisioner_requirement_labels_propagate(env):
    """suite_test.go:564-605 — In/Gt/Lt requirements become node labels;
    NotIn/Exists/DoesNotExist do not pin values."""
    env.expect_applied(
        make_provisioner(
            name="default",
            labels={"test-key-1": "test-value-1"},
            requirements=[
                req("test-key-2", "In", "test-value-2"),
                req("test-key-3", "NotIn", "test-value-3"),
                req("test-key-4", "Lt", "4"),
                req("test-key-5", "Gt", "5"),
                req("test-key-6", "Exists"),
                req("test-key-7", "DoesNotExist"),
            ],
        )
    )
    pod = make_pod(requests={"cpu": "1"})
    env.expect_provisioned(pod)
    node = env.expect_scheduled(pod)
    labels = node.metadata.labels
    assert labels.get("test-key-1") == "test-value-1"
    assert labels.get("test-key-2") == "test-value-2"
    assert labels.get("test-key-3") != "test-value-3"
    assert int(labels["test-key-4"]) < 4
    assert int(labels["test-key-5"]) > 5
    assert "test-key-6" in labels
    assert "test-key-7" not in labels


def test_machine_request_carries_requirements_and_provider(env):
    """suite_test.go:648-712 + 819-859 — the Create call's machine spec
    carries the merged requirements and the compatibility provider
    annotation."""
    env.expect_applied(
        make_provisioner(
            name="default",
            requirements=[req(LABEL_TOPOLOGY_ZONE, "In", "test-zone-2")],
        )
    )
    pod = make_pod(requests={"cpu": "1"})
    env.expect_provisioned(pod)
    env.expect_scheduled(pod)
    call = env.cloud_provider.create_calls[0]
    reqs = {r.key: r for r in call.spec.requirements}
    assert reqs[LABEL_TOPOLOGY_ZONE].values == ["test-zone-2"]
    assert api_labels.PROVISIONER_NAME_LABEL_KEY in reqs
    assert api_labels.PROVIDER_COMPATIBILITY_ANNOTATION_KEY in call.metadata.annotations


def test_machine_request_includes_daemon_overhead_requests(env):
    """suite_test.go:860-918 — machine resource requests include matching
    daemonset requests."""
    env.expect_applied(make_provisioner(name="default"),
                       make_daemonset(requests={"cpu": "1", "memory": "1Gi"}))
    pod = make_pod(requests={"cpu": "1", "memory": "1Gi"})
    env.expect_provisioned(pod)
    env.expect_scheduled(pod)
    call = env.cloud_provider.create_calls[0]
    assert call.spec.resources.requests.get("cpu", 0.0) >= 2.0
    assert call.spec.resources.requests.get("memory", 0.0) >= 2 * 2**30


def test_schedules_to_storage_class_zones(env):
    """suite_test.go:974-998 — an unbound PVC pins the pod to the storage
    class's allowed zones; incompatible pod zones fail."""
    env.expect_applied(
        make_provisioner(name="default"),
        make_storage_class("zonal-sc", "fake.csi", zones=["test-zone-3"]),
        make_pvc("zonal-claim", storage_class="zonal-sc"),
    )
    pod = make_pod(requests={"cpu": "1"})
    pod.spec.volumes.append(pvc_volume("zonal-claim"))
    env.expect_provisioned(pod)
    node = env.expect_scheduled(pod)
    assert node.metadata.labels[LABEL_TOPOLOGY_ZONE] == "test-zone-3"

    incompatible = make_pod(
        requests={"cpu": "1"}, node_selector={LABEL_TOPOLOGY_ZONE: "test-zone-1"}
    )
    incompatible.spec.volumes.append(pvc_volume("zonal-claim"))
    env.expect_provisioned(incompatible)
    env.expect_not_scheduled(incompatible)


def test_schedules_to_bound_volume_zones(env):
    """suite_test.go:999-1010."""
    env.expect_applied(
        make_provisioner(name="default"),
        make_pv("bound-pv", zones=["test-zone-2"]),
        make_pvc("bound-claim", volume_name="bound-pv"),
    )
    pod = make_pod(requests={"cpu": "1"})
    pod.spec.volumes.append(pvc_volume("bound-claim"))
    env.expect_provisioned(pod)
    node = env.expect_scheduled(pod)
    assert node.metadata.labels[LABEL_TOPOLOGY_ZONE] == "test-zone-2"
