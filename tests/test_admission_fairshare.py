"""Fair-share admission (ISSUE 17): weighted per-tenant queueing, EDF
dispatch within a tenant, per-tenant quotas/retry-after hints, the retry
budget that stops a storm, and the closed SLO->brownout loop that demotes
only the burning tenant."""
import threading
import time

import pytest

from karpenter_core_tpu import chaos
from karpenter_core_tpu.obs import reqctx
from karpenter_core_tpu.solver.host import (
    AdmissionGate,
    BrownoutLadder,
    DEADLINE_VIOLATIONS_TOTAL,
    GATE_DEMOTIONS_TOTAL,
)
from karpenter_core_tpu.solver.service import (
    SOLVER_RETRY_BUDGET_EXHAUSTED,
    SolverDeadlineExceededError,
    SolverResourceExhaustedError,
)
from karpenter_core_tpu.testing import FakeClock
from karpenter_core_tpu.utils.backoff import RetryBudget


@pytest.fixture(autouse=True)
def _clean_slate():
    """Chaos off and a fresh tenant-slot table around every test: this
    suite mints many tenant names, and leaking them into the process-wide
    guard would overflow OTHER suites' tenants into the `other` label."""
    chaos.reset()
    reqctx.TENANTS.reset()
    yield
    chaos.reset()
    reqctx.TENANTS.reset()


def _occupied_gate(**kwargs):
    gate = AdmissionGate(name="fairshare-test", **kwargs)
    release = threading.Event()
    started = threading.Event()

    def occupy():
        with gate.admitted():
            started.set()
            release.wait(20)

    t = threading.Thread(target=occupy, daemon=True, name="gate-occupier")
    t.start()
    assert started.wait(5)
    return gate, release, t


def _start_waiter(gate, tenant, order, tag, deadline_s=None):
    def run():
        with reqctx.bind(reqctx.RequestContext(tenant=tenant)):
            with gate.admitted(deadline_s=deadline_s):
                order.append(tag)

    t = threading.Thread(target=run, daemon=True, name=f"waiter-{tag}")
    t.start()
    return t


def _wait_queued(gate, n):
    """Block until *n* tickets sit in the sub-queues — the serialization
    point that makes multi-thread enqueue order deterministic."""
    for _ in range(400):
        if sum(gate.stats()["queues"].values()) >= n:
            return
        time.sleep(0.01)
    raise AssertionError(f"never saw {n} queued: {gate.stats()}")


# -- dispatch order: WFQ across tenants, EDF within one --------------------


def test_wfq_dispatch_alternates_across_tenants():
    """Three queued requests from tenant A and one from tenant B, equal
    weights: dispatch order is A,B,A,A — B is served after ONE of A's
    requests, not after all three. FIFO would starve B behind A's backlog;
    deficit-round-robin cannot."""
    gate, release, t = _occupied_gate(max_queue=8)
    order = []
    waiters = []
    for i, (tenant, tag) in enumerate(
        [("wfq-a", "a1"), ("wfq-a", "a2"), ("wfq-a", "a3"), ("wfq-b", "b1")]
    ):
        waiters.append(_start_waiter(gate, tenant, order, tag))
        _wait_queued(gate, i + 1)
    release.set()
    t.join(5)
    for w in waiters:
        w.join(5)
    assert order == ["a1", "b1", "a2", "a3"], order


def test_drr_weights_shape_dispatch_share():
    """A tenant weighted 0.5 accumulates a dispatch credit every OTHER
    ring rotation: with both backlogged, the weight-1.0 tenant gets two
    dispatches for each of the light tenant's one."""
    gate, release, t = _occupied_gate(
        max_queue=8, weights={"wfq-lite": 0.5}
    )
    order = []
    waiters = []
    plan = [("wfq-hvy", "h1"), ("wfq-hvy", "h2"), ("wfq-hvy", "h3"),
            ("wfq-hvy", "h4"), ("wfq-lite", "l1"), ("wfq-lite", "l2")]
    for i, (tenant, tag) in enumerate(plan):
        waiters.append(_start_waiter(gate, tenant, order, tag))
        _wait_queued(gate, i + 1)
    release.set()
    t.join(5)
    for w in waiters:
        w.join(5)
    assert order == ["h1", "h2", "l1", "h3", "h4", "l2"], order


def test_edf_orders_within_tenant():
    """Within one tenant's sub-queue the EARLIEST deadline dispatches
    first, regardless of arrival order."""
    gate, release, t = _occupied_gate(max_queue=8)
    order = []
    waiters = []
    for i, deadline in enumerate([30.0, 10.0, 20.0]):
        waiters.append(_start_waiter(
            gate, "edf-team", order, deadline, deadline_s=deadline
        ))
        _wait_queued(gate, i + 1)
    release.set()
    t.join(5)
    for w in waiters:
        w.join(5)
    assert order == [10.0, 20.0, 30.0], order


# -- per-tenant quota and retry-after -------------------------------------


def test_tenant_quota_sheds_only_the_flooder():
    """With tenant_quota=1 the flooder's SECOND queued request sheds
    (typed, retry-after hint attached) while another tenant still queues
    freely — the quota isolates the offender, not the gate."""
    gate, release, t = _occupied_gate(max_queue=8, tenant_quota=1)
    order = []
    w1 = _start_waiter(gate, "quota-flood", order, "a1")
    _wait_queued(gate, 1)
    with reqctx.bind(reqctx.RequestContext(tenant="quota-flood")):
        with pytest.raises(SolverResourceExhaustedError) as exc:
            with gate.admitted():
                pass
    assert exc.value.shed_reason == "tenant_quota"
    assert exc.value.retry_after_s and exc.value.retry_after_s > 0
    assert "retry_after_ms=" in str(exc.value)
    # the calm tenant is NOT shed by the flooder's quota
    w2 = _start_waiter(gate, "quota-calm", order, "b1")
    _wait_queued(gate, 2)
    release.set()
    t.join(5)
    w1.join(5)
    w2.join(5)
    stats = gate.stats()
    assert set(order) == {"a1", "b1"}
    assert list(stats["shed_by_tenant"]) == ["quota-flood"]
    assert stats["shed_by_tenant"]["quota-flood"]["tenant_quota"] == 1
    assert stats["dispatched_by_tenant"] == {"quota-flood": 1, "quota-calm": 1}


def test_retry_after_hint_is_per_tenant_ema():
    """The shed's retry-after hint is the REQUESTING tenant's own queue
    depth x its own service-time EMA — one tenant's slow solves no longer
    poison the hint for everyone. The global EMA is only the cold-start
    fallback."""
    gate, release, t = _occupied_gate(max_queue=0)
    gate._tenant_ema["ema-slow"] = 2.0
    gate._ema = 0.05
    with reqctx.bind(reqctx.RequestContext(tenant="ema-slow")):
        with pytest.raises(SolverResourceExhaustedError) as exc_slow:
            with gate.admitted():
                pass
    with reqctx.bind(reqctx.RequestContext(tenant="ema-fresh")):
        with pytest.raises(SolverResourceExhaustedError) as exc_fresh:
            with gate.admitted():
                pass
    # depth is 1 (the occupier) for both; only the EMA differs
    assert exc_slow.value.retry_after_s == pytest.approx(4.0)
    assert exc_fresh.value.retry_after_s == pytest.approx(0.1)
    release.set()
    t.join(5)


def test_retry_after_hint_rides_trailing_metadata_per_tenant():
    """Satellite 1 wire check: the trailing-metadata retry-after hint the
    client parses back reflects the REQUESTING tenant's EMA, per tenant,
    over a real gRPC hop."""
    from karpenter_core_tpu.cloudprovider import fake
    from karpenter_core_tpu.solver.service import RemoteSolver, serve
    from karpenter_core_tpu.testing import make_pod, make_provisioner

    server, port, service = serve(max_workers=4, max_queue=0)
    try:
        service.admission._tenant_ema["hint-slow"] = 2.0
        service.admission._ema = 0.05
        gate_cm = service.admission.admitted()
        gate_cm.__enter__()  # occupy: queue capacity is zero, RPCs shed
        client = RemoteSolver(f"127.0.0.1:{port}", rpc_retries=0)
        pods = [make_pod(requests={"cpu": "1"}) for _ in range(4)]
        args = (pods, [make_provisioner(name="d")],
                {"d": fake.instance_types(4)})
        with reqctx.bind(reqctx.RequestContext(tenant="hint-slow")):
            with pytest.raises(SolverResourceExhaustedError) as exc_slow:
                client.solve(*args)
        with reqctx.bind(reqctx.RequestContext(tenant="hint-fresh")):
            with pytest.raises(SolverResourceExhaustedError) as exc_fresh:
                client.solve(*args)
        assert exc_slow.value.retry_after_s == pytest.approx(4.0, abs=0.01)
        assert exc_fresh.value.retry_after_s == pytest.approx(0.1, abs=0.01)
        gate_cm.__exit__(None, None, None)
    finally:
        server.stop(0)


# -- retry budget ----------------------------------------------------------


def test_retry_budget_token_bucket():
    clock = FakeClock()
    rb = RetryBudget(capacity=2.0, refill_per_s=1.0, clock=clock)
    assert rb.try_spend("a")
    assert rb.try_spend("a")
    assert not rb.try_spend("a"), "capacity spent"
    assert rb.try_spend("b"), "per-key isolation: b has its own bucket"
    clock.advance(1.0)
    assert rb.try_spend("a"), "continuous refill"
    assert not rb.try_spend("a")
    clock.advance(100.0)
    assert rb.try_spend("a") and rb.try_spend("a")
    assert not rb.try_spend("a"), "refill caps at capacity"
    rb2 = RetryBudget(capacity=1.0, refill_per_s=0.0, clock=clock)
    assert rb2.try_spend(None), "None folds into the unbound '' bucket"
    assert not rb2.try_spend(""), "... which is one shared bucket"
    stats = rb.stats()
    assert stats["capacity"] == 2.0
    assert stats["denied_total"] >= 3
    assert stats["spent_total"] >= 5


def test_retry_budget_stops_retry_storm_per_tenant():
    """An exhausted budget raises the original error instead of retrying —
    and exhausts PER TENANT: the unbound storm draining '' leaves a bound
    tenant's bucket full."""
    from karpenter_core_tpu.solver import service_pb2 as pb
    from karpenter_core_tpu.solver.fallback import CircuitBreaker
    from karpenter_core_tpu.solver.service import (
        RemoteSolver,
        SolverUnavailableError,
    )

    fault = chaos.arm(chaos.SOLVER_RPC, error="unavailable")
    client = RemoteSolver(
        "127.0.0.1:1", rpc_retries=10, rpc_retry_base=0.001,
        breaker=CircuitBreaker(failure_threshold=100),
        retry_budget=RetryBudget(capacity=2.0, refill_per_s=0.0),
    )
    with pytest.raises(SolverUnavailableError):
        client._invoke_solve(pb.SolveRequest(), None)
    assert fault.injected == 3, "1 initial + the 2 budget-allowed retries"
    with pytest.raises(SolverUnavailableError):
        client._invoke_solve(pb.SolveRequest(), None)
    assert fault.injected == 4, "bucket empty: no retry at all"
    before = SOLVER_RETRY_BUDGET_EXHAUSTED.get({"tenant": "storm-b"}) or 0
    with reqctx.bind(reqctx.RequestContext(tenant="storm-b")):
        with pytest.raises(SolverUnavailableError):
            client._invoke_solve(pb.SolveRequest(), None)
    assert fault.injected == 7, (
        "the bound tenant's bucket is untouched by the unbound storm"
    )
    assert (
        SOLVER_RETRY_BUDGET_EXHAUSTED.get({"tenant": "storm-b"}) or 0
    ) == before + 1


# -- the brownout ladder (closed SLO loop) ---------------------------------


def test_ladder_demotes_and_promotes_with_hysteresis():
    clock = FakeClock()
    burns = {"lad-osc": 5.0}
    ladder = BrownoutLadder(
        lambda t: burns.get(t, 0.0), demote_at=1.0, promote_below=0.5,
        hold_s=10.0, eval_interval_s=1.0, clock=clock,
    )
    # first demotion is immediate (rung 0 needs no dwell)
    assert ladder.review("lad-osc") == "greedy"
    # rate limit: re-review inside eval_interval_s answers from cache
    burns["lad-osc"] = 0.0
    assert ladder.review("lad-osc") == "greedy"
    burns["lad-osc"] = 5.0
    # escalation needs the dwell: 1.5s in is still greedy
    clock.advance(1.5)
    assert ladder.review("lad-osc") == "greedy"
    clock.advance(10.0)
    assert ladder.review("lad-osc") == "shed"
    # burn stops: promotion ALSO waits out the dwell (hysteresis)
    burns["lad-osc"] = 0.0
    clock.advance(1.5)
    assert ladder.review("lad-osc") == "shed"
    clock.advance(10.0)
    assert ladder.review("lad-osc") == "greedy"
    clock.advance(10.5)
    assert ladder.review("lad-osc") == "device"
    assert ladder.demotions_total == 2
    assert ladder.promotions_total == 2
    st = ladder.stats()
    assert st["tenants"]["lad-osc"]["level"] == "device"
    # a bystander that never burned never leaves the device rung
    assert ladder.review("lad-calm") == "device"
    assert ladder.demotions_total == 2


def test_ladder_sick_probe_holds_rung():
    """A failing burn probe HOLDS the current rung: the ladder acts on
    absolute SLO evidence, and a sick probe is not evidence (contrast with
    the depth-band preference hook, which fails closed)."""
    clock = FakeClock()
    state = {"burn": 5.0}

    def probe(tenant):
        if state["burn"] is None:
            raise RuntimeError("slo engine sick")
        return state["burn"]

    ladder = BrownoutLadder(
        probe, demote_at=1.0, promote_below=0.5, hold_s=1.0,
        eval_interval_s=0.0, clock=clock,
    )
    assert ladder.review("lad-sick") == "greedy"
    state["burn"] = None
    clock.advance(50.0)
    assert ladder.review("lad-sick") == "greedy", "sick probe holds"
    state["burn"] = 0.0
    clock.advance(50.0)
    assert ladder.review("lad-sick") == "device"


def test_ladder_demotes_only_burning_tenant_at_gate():
    """Gate integration: the burning tenant walks device -> greedy ->
    shed (each rung a distinct typed shed) while a calm tenant dispatches
    throughout; when the burn stops, hysteresis walks the burner back up
    and it dispatches again. Demotions tick the counter per tenant."""
    clock = FakeClock()
    burns = {"lad-hot": 5.0}
    ladder = BrownoutLadder(
        lambda t: burns.get(t, 0.0), demote_at=1.0, promote_below=0.5,
        hold_s=5.0, eval_interval_s=0.0, clock=clock,
    )
    gate = AdmissionGate(name="ladder-gate", max_queue=4, ladder=ladder)
    greedy_before = GATE_DEMOTIONS_TOTAL.get(
        {"tenant": "lad-hot", "reason": "greedy"}) or 0
    shed_before = GATE_DEMOTIONS_TOTAL.get(
        {"tenant": "lad-hot", "reason": "shed"}) or 0

    with reqctx.bind(reqctx.RequestContext(tenant="lad-hot")):
        with pytest.raises(SolverResourceExhaustedError) as exc:
            with gate.admitted():
                pass
    assert exc.value.shed_reason == "brownout"
    with reqctx.bind(reqctx.RequestContext(tenant="lad-cold")):
        with gate.admitted():
            pass  # the calm tenant rides through
    clock.advance(6.0)  # past hold_s: the still-burning tenant escalates
    with reqctx.bind(reqctx.RequestContext(tenant="lad-hot")):
        with pytest.raises(SolverResourceExhaustedError) as exc:
            with gate.admitted():
                pass
    assert exc.value.shed_reason == "brownout_shed"
    assert exc.value.retry_after_s == pytest.approx(ladder.hold_s)
    # the flood stops: two dwells walk shed -> greedy -> device
    burns["lad-hot"] = 0.0
    clock.advance(6.0)
    with reqctx.bind(reqctx.RequestContext(tenant="lad-hot")):
        with pytest.raises(SolverResourceExhaustedError):
            with gate.admitted():
                pass  # promoted to greedy: still shedding to the fallback
    clock.advance(6.0)
    with reqctx.bind(reqctx.RequestContext(tenant="lad-hot")):
        with gate.admitted():
            pass  # back on the device rung
    assert ladder.demotions_total == 2 and ladder.promotions_total == 2
    assert (GATE_DEMOTIONS_TOTAL.get(
        {"tenant": "lad-hot", "reason": "greedy"}) or 0) == greedy_before + 1
    assert (GATE_DEMOTIONS_TOTAL.get(
        {"tenant": "lad-hot", "reason": "shed"}) or 0) == shed_before + 1
    stats = gate.stats()
    assert stats["ladder"]["tenants"]["lad-hot"]["level"] == "device"
    assert "lad-cold" not in stats["ladder"]["tenants"] or (
        stats["ladder"]["tenants"]["lad-cold"]["level"] == "device"
    )
    assert stats["shed_by_tenant"]["lad-hot"]["brownout"] == 2
    assert stats["shed_by_tenant"]["lad-hot"]["brownout_shed"] == 1
    assert "lad-cold" not in stats["shed_by_tenant"]


# -- deadlines -------------------------------------------------------------


def test_deadline_expired_attributed_per_tenant():
    """A request that expires while queued sheds as deadline_expired,
    billed to ITS tenant: the stage=queue violations series ticks, the
    per-tenant expired_in_queue stat ticks, and the structural
    stage=dispatch counter stays zero."""
    gate, release, t = _occupied_gate(max_queue=4)
    labels = {"gate": "fairshare-test", "stage": "queue",
              "tenant": "exp-team"}
    before = DEADLINE_VIOLATIONS_TOTAL.get(labels) or 0
    with reqctx.bind(reqctx.RequestContext(tenant="exp-team")):
        with pytest.raises(SolverDeadlineExceededError) as exc:
            with gate.admitted(deadline_s=0.3):
                pass
    assert exc.value.shed_reason == "deadline_expired"
    assert (DEADLINE_VIOLATIONS_TOTAL.get(labels) or 0) == before + 1
    stats = gate.stats()
    assert stats["expired_in_queue"] == {"exp-team": 1}
    assert stats["shed_by_tenant"]["exp-team"]["deadline_expired"] == 1
    assert stats["deadline_violations"] == 0, (
        "stage=dispatch is structural: queue expiries never reach it"
    )
    release.set()
    t.join(5)


def test_ctx_deadline_tightens_gate_budget():
    """RequestContext.deadline_s is CONSUMED by the gate: an
    already-expired context budget is never dispatched, even through an
    idle gate."""
    gate = AdmissionGate(name="ctx-deadline", max_queue=4)
    assert reqctx.current_deadline() is None
    with reqctx.bind(reqctx.RequestContext(tenant="ctxdl", deadline_s=0.0)):
        assert reqctx.current_deadline() == 0.0
        with pytest.raises(SolverDeadlineExceededError):
            with gate.admitted(deadline_s=30.0):  # ctx tightens 30 -> 0
                pass
    assert gate.dispatched_total == 0
    assert gate.stats()["expired_in_queue"] == {"ctxdl": 1}


# -- the SLO feedback source ----------------------------------------------


def test_admission_totals_feed_fast_burn():
    """admission_totals() is the SLO engine's collect source: capacity
    sheds burn, dispatches don't, and fast_burn() sees the flooder (and
    ONLY the flooder) burning over the fast window."""
    from karpenter_core_tpu.obs.slo import Objective, SloEngine

    gate, release, t = _occupied_gate(max_queue=0)
    for _ in range(3):
        with reqctx.bind(reqctx.RequestContext(tenant="totals-flood")):
            with pytest.raises(SolverResourceExhaustedError):
                with gate.admitted():
                    pass
    release.set()
    t.join(5)
    with reqctx.bind(reqctx.RequestContext(tenant="totals-calm")):
        with gate.admitted():
            pass
    totals = gate.admission_totals()
    assert totals["totals-flood"] == (0, 3)
    assert totals["totals-calm"] == (1, 1)
    # the aggregate counts the unbound occupier's dispatch too
    assert totals[None] == (2, 5)
    engine = SloEngine(
        [Objective(name="gate-admission", histogram=None, threshold_s=0.0,
                   target=0.95, collect=gate.admission_totals)],
        windows=(("2s", 2.0), ("10s", 10.0)),
    )
    assert engine.fast_burn("totals-flood") > 1.0
    assert engine.fast_burn("totals-calm") == 0.0
    assert engine.fast_burn(None) == 0.0


def test_ladder_sheds_excluded_from_burn():
    """Ladder sheds must NOT count as burn: if they did, a demoted
    tenant's residual traffic would hold its burn above the promote
    threshold forever and the closed loop could never recover."""
    gate, release, t = _occupied_gate(max_queue=0)
    with reqctx.bind(reqctx.RequestContext(tenant="loop-a")):
        with pytest.raises(SolverResourceExhaustedError):
            with gate.admitted():
                pass
    release.set()
    t.join(5)
    assert gate.admission_totals()["loop-a"] == (0, 1)
    # now shed the same tenant at the LADDER: totals must not move
    gate.ladder = BrownoutLadder(
        lambda t: 5.0, hold_s=60.0, eval_interval_s=0.0, clock=FakeClock(),
    )
    for _ in range(4):
        with reqctx.bind(reqctx.RequestContext(tenant="loop-a")):
            with pytest.raises(SolverResourceExhaustedError) as exc:
                with gate.admitted():
                    pass
        assert exc.value.shed_reason == "brownout"
    assert gate.admission_totals()["loop-a"] == (0, 1), (
        "brownout sheds are excluded: the loop must see the flood stop"
    )


# -- chaos flood point -----------------------------------------------------


def test_chaos_flood_reattributes_to_synthetic_tenant():
    """solver.gate.flood does not ERROR the request — it re-attributes it
    to the synthetic chaos-flood tenant, so quota/brownout isolation can
    be drilled mid-churn without touching real tenants' accounting."""
    from karpenter_core_tpu.solver.host import CHAOS_FLOOD_TENANT

    gate = AdmissionGate(name="chaos-flood-gate", max_queue=4)
    fault = chaos.arm(chaos.SOLVER_GATE_FLOOD, error="exhausted", times=1)
    with gate.admitted():
        pass  # no tenant bound; the injection re-attributes, never raises
    assert fault.injected == 1
    assert gate.stats()["dispatched_by_tenant"] == {CHAOS_FLOOD_TENANT: 1}
    with gate.admitted():
        pass  # fault exhausted: back to the unbound sub-queue
    assert gate.stats()["dispatched_by_tenant"] == {CHAOS_FLOOD_TENANT: 1}
