"""Bucket-ladder edge cases (ISSUE 7): tier boundaries, overflow past the
top rung, dictionary/node growth bumping tiers, batcher clamping, and the
prewarm-vs-live-solve race.

The ladder's contract: every solve-shaping axis pads to a value from the
FIXED tier table (api/settings.py), so the compiled-program set is bounded
and enumerable — `compiled_programs` stays O(tiers) under mixed-geometry
churn (the structural tripwire for that lives in test_perf_floor.py) and
the startup prewarm can compile everything ahead of traffic.
"""
import threading

import numpy as np
import pytest

import karpenter_core_tpu.api.settings as api_settings
from karpenter_core_tpu.api.settings import (
    DEFAULT_BUCKET_LADDER,
    GeometryTier,
    Settings,
    parse_bucket_ladder,
)
from karpenter_core_tpu.cloudprovider import fake
from karpenter_core_tpu.solver.encode import encode_snapshot, ladder_pad
from karpenter_core_tpu.solver.tpu_solver import TPUSolver, solve_geometry
from karpenter_core_tpu.testing import make_pod, make_provisioner

SMALL_LADDER = (
    GeometryTier("S", pods=128, items=32, instance_types=16, existing_nodes=8),
    GeometryTier("M", pods=512, items=64, instance_types=32, existing_nodes=16),
)


@pytest.fixture()
def small_ladder():
    """Install a two-rung ladder for the duration of a test."""
    prev = api_settings.current()
    api_settings.set_current(Settings(bucket_ladder=SMALL_LADDER))
    yield SMALL_LADDER
    api_settings.set_current(prev)


def _pods(n, prefix="p"):
    return [
        make_pod(labels={"app": f"{prefix}-{i}"},
                 requests={"cpu": str(0.1 + 0.01 * (i % 7))})
        for i in range(n)
    ]


def _universe(n=5):
    return fake.instance_types(n)


# -- ladder_pad semantics ----------------------------------------------------


def test_ladder_pad_snaps_to_tier_values():
    assert ladder_pad(0, SMALL_LADDER, "items", 32) == 0
    assert ladder_pad(1, SMALL_LADDER, "items", 32) == 32
    assert ladder_pad(32, SMALL_LADDER, "items", 32) == 32  # exact boundary
    assert ladder_pad(33, SMALL_LADDER, "items", 32) == 64  # one past it
    assert ladder_pad(64, SMALL_LADDER, "items", 32) == 64


def test_ladder_pad_overflow_continues_pow2_and_counts():
    from karpenter_core_tpu.metrics.registry import NAMESPACE, REGISTRY

    # force the lazy counter to exist, then measure the delta
    before_pad = ladder_pad(65, SMALL_LADDER, "items", 32)  # overflow: 128
    counter = REGISTRY.counter(f"{NAMESPACE}_bucket_overflow_total")
    before = counter.get({"axis": "items"})
    assert before_pad == 128
    assert ladder_pad(300, SMALL_LADDER, "items", 32) == 512
    assert counter.get({"axis": "items"}) == before + 1


def test_ladder_pad_without_ladder_is_pow2():
    assert ladder_pad(20, (), "items", 32) == 32
    assert ladder_pad(100, (), "items", 32) == 128


# -- settings ----------------------------------------------------------------


def test_parse_bucket_ladder_grammar():
    tiers = parse_bucket_ladder("S:128:32:16:8, XL:65536:2048:512:1024")
    assert [t.name for t in tiers] == ["S", "XL"]
    assert tiers[1].instance_types == 512
    with pytest.raises(ValueError):
        parse_bucket_ladder("S:128:32:16")  # wrong arity
    with pytest.raises(ValueError):
        parse_bucket_ladder("S:128:32:16:8,M:64:64:32:16")  # non-monotonic
    with pytest.raises(ValueError):
        parse_bucket_ladder("")


def test_settings_config_map_parses_ladder():
    s = Settings.from_config_map({"bucketLadder": "S:16:8:4:2,M:32:16:8:4"})
    assert len(s.bucket_ladder) == 2
    assert s.bucket_ladder[0].pods == 16


def test_effective_batch_max_pods_clamps_to_top_rung():
    s = Settings(bucket_ladder=SMALL_LADDER)
    # unset cap -> the ladder's top rung IS the cap (a bigger pass would
    # mint an unlisted geometry)
    assert s.effective_batch_max_pods() == 512
    s.batch_max_pods = 100
    assert s.effective_batch_max_pods() == 100
    s.batch_max_pods = 100000
    assert s.effective_batch_max_pods() == 512
    # no ladder: the configured cap passes through untouched
    s2 = Settings(bucket_ladder=(), batch_max_pods=7)
    assert s2.effective_batch_max_pods() == 7


def test_steady_state_tier_prefers_batch_cap_rung():
    s = Settings(bucket_ladder=SMALL_LADDER, batch_max_pods=16)
    assert s.steady_state_tier().name == "S"
    s.batch_max_pods = 0
    assert s.steady_state_tier().name == "M"


# -- geometry snapping -------------------------------------------------------


def test_tier_boundary_batches_share_one_program(small_ladder):
    """Workloads at 30 and exactly-32 distinct items share one compiled
    entry; 40 items bumps to the next rung — and both rungs' axes are
    LISTED tier values, never ad-hoc pow2."""
    provisioners = [make_provisioner(name="default")]
    its = {"default": _universe(5)}
    solver = TPUSolver(max_nodes=48)
    solver.solve(_pods(30), provisioners, its)
    solver.solve(_pods(32), provisioners, its)
    assert len(solver._compiled) == 1
    solver.solve(_pods(40), provisioners, its)
    assert len(solver._compiled) == 2
    item_values = {t.items for t in small_ladder}
    type_values = {t.instance_types for t in small_ladder}
    for key in solver._compiled:
        geom = key[0]
        P_axis, _J, T_axis = geom[0], geom[1], geom[2]
        assert P_axis in item_values
        assert T_axis in type_values


def test_node_growth_bumps_existing_tier(small_ladder):
    """6 existing nodes pad to the S rung (8); 10 nodes cross it and pad
    to the M rung (16) — a new listed geometry, not pow2's 16... which
    here coincides, so assert through the tier table."""
    from karpenter_core_tpu.state.node import StateNode
    from karpenter_core_tpu.testing import make_node

    universe = _universe(5)
    provisioners = [make_provisioner(name="default")]
    its = {"default": universe}

    def nodes(n):
        out = []
        for e in range(n):
            it = universe[e % len(universe)]
            out.append(StateNode(node=make_node(
                name=f"gn-{e}",
                labels={
                    "karpenter.sh/provisioner-name": "default",
                    "karpenter.sh/initialized": "true",
                    "node.kubernetes.io/instance-type": it.name,
                    "karpenter.sh/capacity-type": "on-demand",
                    "topology.kubernetes.io/zone": "test-zone-1",
                },
                capacity={k: str(v) for k, v in it.capacity.items()},
            )))
        return out

    snap6 = encode_snapshot(_pods(10), provisioners, its, None, nodes(6),
                            max_nodes=48)
    snap10 = encode_snapshot(_pods(10), provisioners, its, None, nodes(10),
                             max_nodes=48)
    e_values = {t.existing_nodes for t in small_ladder}
    E6 = snap6.exist_used.shape[0]
    E10 = snap10.exist_used.shape[0]
    assert E6 == 8 and E10 == 16
    assert {E6, E10} <= e_values
    assert solve_geometry(snap6, 48)[3] == 8
    assert solve_geometry(snap10, 48)[3] == 16


def test_overflow_past_top_rung_still_solves(small_ladder):
    """A direct solver call past the top rung's items axis (the batcher
    would have split it — Settings.effective_batch_max_pods) falls back to
    pow2 padding and still answers correctly."""
    provisioners = [make_provisioner(name="default")]
    its = {"default": _universe(3)}
    solver = TPUSolver(max_nodes=256)
    n = 100  # > M.items (64) distinct specs -> overflow items axis
    res = solver.solve(_pods(n), provisioners, its)
    assert res.pod_count_new() + res.pod_count_existing() == n
    geom = next(iter(solver._compiled))[0]
    assert geom[0] == 128  # pow2 continuation above the 64 rung


# -- prewarm ----------------------------------------------------------------


def test_prewarm_then_live_solve_hits_cache(small_ladder):
    from karpenter_core_tpu.solver.prewarm import prewarm, synthetic_workload
    from karpenter_core_tpu.utils.compilecache import CACHE_HITS, CACHE_MISSES

    provisioners = [make_provisioner(name="default")]
    its = {"default": _universe(5)}
    solver = TPUSolver(max_nodes=48)
    settings = Settings(bucket_ladder=(SMALL_LADDER[0],))
    outcomes = prewarm(solver, provisioners, its, settings=settings)
    assert outcomes == {"S": "compiled"}
    assert len(solver._compiled) == 1
    (fn, pre_fn) = next(iter(solver._compiled.values()))
    assert fn.aot is not None  # the AOT executable is attached

    hits0 = CACHE_HITS.get({"site": "tpu_solver"})
    misses0 = CACHE_MISSES.get({"site": "tpu_solver"})
    pods, nodes = synthetic_workload(SMALL_LADDER[0], provisioners, its)
    res = solver.solve(pods[:40], provisioners, its, state_nodes=nodes)
    assert res.pod_count_new() + res.pod_count_existing() == 40
    assert len(solver._compiled) == 1  # no second program minted
    assert CACHE_HITS.get({"site": "tpu_solver"}) == hits0 + 1
    assert CACHE_MISSES.get({"site": "tpu_solver"}) == misses0


def test_prewarm_vs_live_solve_race(small_ladder):
    """A solve arriving while the prewarm thread compiles the same tier
    must produce a correct answer and no duplicate compile: the per-key
    lock serializes creation, so exactly one entry exists afterward."""
    from karpenter_core_tpu.solver.prewarm import prewarm, synthetic_workload

    provisioners = [make_provisioner(name="default")]
    its = {"default": _universe(5)}
    solver = TPUSolver(max_nodes=48)
    settings = Settings(bucket_ladder=(SMALL_LADDER[0],))
    pods, nodes = synthetic_workload(SMALL_LADDER[0], provisioners, its)

    outcomes = {}
    t = threading.Thread(
        target=lambda: outcomes.update(
            prewarm(solver, provisioners, its, settings=settings)
        ),
        daemon=True, name="test-prewarm",
    )
    t.start()
    res = solver.solve(pods[:40], provisioners, its, state_nodes=nodes)
    t.join(timeout=300)
    assert not t.is_alive()
    assert res.pod_count_new() + res.pod_count_existing() == 40
    # whoever won built the single entry; the loser adopted it
    assert len(solver._compiled) == 1
    assert outcomes["S"] in ("compiled", "cached")
    # the answer served mid-prewarm is byte-identical to a post-prewarm
    # solve of the same batch (placement parity across the race)
    res2 = solver.solve(pods[:40], provisioners, its, state_nodes=nodes)
    placed = lambda r: sorted(  # noqa: E731
        (p.metadata.name, m.template.provisioner_name)
        for m in r.new_machines for p in m.pods
    )
    existing = lambda r: sorted(  # noqa: E731
        (p.metadata.name, n.name()) for n, ps in r.existing_assignments
        for p in ps
    )
    assert placed(res) == placed(res2)
    assert existing(res) == existing(res2)


def test_synthetic_workload_lands_on_tier(small_ladder):
    """The prewarm's synthetic snapshot must mint EXACTLY the tier's
    geometry — that equality is what makes prewarmed entries hittable."""
    from karpenter_core_tpu.solver.prewarm import synthetic_workload

    provisioners = [make_provisioner(name="default")]
    its = {"default": _universe(5)}
    tier = SMALL_LADDER[1]
    pods, nodes = synthetic_workload(tier, provisioners, its)
    snap = encode_snapshot(pods, provisioners, its, None, nodes, max_nodes=48)
    geom = solve_geometry(snap, 48)
    assert geom[0] == tier.items  # item axis
    # the type axis rides the REAL universe (5 types -> the S rung), same
    # snap a live solve against this universe makes — that equality, not
    # the tier's own value, is what makes the prewarmed entry hittable
    assert geom[2] == ladder_pad(5, small_ladder, "instance_types", 1)
    assert geom[3] == tier.existing_nodes  # existing axis
    assert snap.item_pad == tier.items
