"""HTTPS admission serving + cert rotation (webhooks/server.py), the
out-of-process transport for the in-process admission brain
(reference pkg/webhooks/webhooks.go:17-63).
"""
import base64
import datetime
import json
import ssl
import urllib.request

import pytest

from karpenter_core_tpu.kube.client import InMemoryKubeClient
from karpenter_core_tpu.kube.serialization import from_k8s_dict, to_k8s_dict
from karpenter_core_tpu.webhooks.server import (
    CERT_SECRET_NAME,
    HAVE_CRYPTOGRAPHY,
    CertManager,
    WebhookServer,
    cert_expiry,
    generate_self_signed_cert,
)

# the TLS cert path needs the optional `cryptography` dependency (absent
# from the solver image): the serving/rotation tests skip cleanly instead
# of erroring — webhooks/server.py degrades the same way at runtime
# (require_cryptography), and the wire-format test below runs either way
requires_cryptography = pytest.mark.skipif(
    not HAVE_CRYPTOGRAPHY,
    reason="webhook TLS tests need the optional `cryptography` package "
    "(webhooks/server.py degrades to in-process admission without it)",
)


def _post(port, path, review):
    ctx = ssl.create_default_context()
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_NONE  # self-signed serving cert
    req = urllib.request.Request(
        f"https://127.0.0.1:{port}{path}",
        data=json.dumps(review).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, context=ctx, timeout=10) as resp:
        return json.loads(resp.read())


def _review(kind, obj, uid="test-uid"):
    return {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "request": {"uid": uid, "kind": {"kind": kind}, "object": obj},
    }


@pytest.fixture()
def server():
    client = InMemoryKubeClient()
    srv = WebhookServer(client, namespace="karpenter")
    port = srv.start()
    yield client, srv, port
    srv.stop()


@requires_cryptography
def test_cert_manager_populates_and_reuses_secret():
    client = InMemoryKubeClient()
    cm = CertManager(client, namespace="karpenter")
    cert1, key1 = cm.reconcile()
    secret = client.get("Secret", "karpenter", CERT_SECRET_NAME)
    assert secret is not None and secret.data["tls.crt"]
    # fresh cert is reused, not regenerated
    cert2, _ = cm.reconcile()
    assert cert2 == cert1


@requires_cryptography
def test_cert_manager_rotates_near_expiry():
    client = InMemoryKubeClient()
    cm = CertManager(client, namespace="karpenter")
    # seed a nearly-expired cert (3 days left < 7-day rotation window)
    old_cert, old_key = generate_self_signed_cert(valid_days=3)
    from karpenter_core_tpu.kube.objects import ObjectMeta, Secret

    client.create(
        Secret(
            metadata=ObjectMeta(name=CERT_SECRET_NAME, namespace="karpenter"),
            data={
                "tls.crt": base64.b64encode(old_cert).decode(),
                "tls.key": base64.b64encode(old_key).decode(),
            },
        )
    )
    new_cert, _ = cm.reconcile()
    assert new_cert != old_cert
    assert cert_expiry(new_cert) > cert_expiry(old_cert)
    stored = client.get("Secret", "karpenter", CERT_SECRET_NAME)
    assert base64.b64decode(stored.data["tls.crt"]) == new_cert


@requires_cryptography
def test_validate_rejects_invalid_provisioner(server):
    _, _, port = server
    bad = {
        "kind": "Provisioner",
        "metadata": {"name": "bad"},
        "spec": {
            "requirements": [
                {"key": "kubernetes.io/hostname", "operator": "In",
                 "values": ["h"]}
            ]
        },
    }
    out = _post(port, "/validate", _review("Provisioner", bad))
    assert out["response"]["allowed"] is False
    assert "hostname" in out["response"]["status"]["message"]


@requires_cryptography
def test_validate_allows_valid_provisioner(server):
    _, _, port = server
    good = {
        "kind": "Provisioner",
        "metadata": {"name": "ok"},
        "spec": {"provider": {"fake": True}},
    }
    out = _post(port, "/validate", _review("Provisioner", good))
    assert out["response"]["allowed"] is True
    assert out["response"]["uid"] == "test-uid"


@requires_cryptography
def test_default_endpoint_returns_patch(server):
    _, _, port = server
    # defaulting adds e.g. the capacity-type requirement default
    obj = {
        "kind": "Provisioner",
        "metadata": {"name": "needs-defaults"},
        "spec": {"provider": {"fake": True}},
    }
    out = _post(port, "/default", _review("Provisioner", obj))
    resp = out["response"]
    assert resp["allowed"] is True
    if "patch" in resp:  # defaulting produced changes
        patch = json.loads(base64.b64decode(resp["patch"]))
        assert patch and patch[0]["path"].startswith("/spec")


def test_serialization_round_trip():
    """from_k8s_dict/to_k8s_dict round-trip the Provisioner CRD with
    camelCase keys and string quantities."""
    from karpenter_core_tpu.api.provisioner import Provisioner

    wire = {
        "metadata": {"name": "p"},
        "spec": {
            "labels": {"team": "a"},
            "taints": [{"key": "k", "value": "v", "effect": "NoSchedule"}],
            "startupTaints": [{"key": "s", "effect": "NoSchedule"}],
            "requirements": [
                {"key": "topology.kubernetes.io/zone", "operator": "In",
                 "values": ["test-zone-1"]}
            ],
            "ttlSecondsAfterEmpty": 30,
            "limits": {"resources": {"cpu": "100", "memory": "100Gi"}},
            "weight": 10,
            "consolidation": {"enabled": True},
            "provider": {"fake": True},
        },
    }
    p = from_k8s_dict(Provisioner, wire)
    assert p.spec.startup_taints[0].key == "s"
    assert p.spec.ttl_seconds_after_empty == 30
    assert p.spec.limits.resources["cpu"] == 100.0
    assert p.spec.limits.resources["memory"] == 100 * 2**30
    assert p.spec.consolidation.enabled is True
    back = to_k8s_dict(p)
    assert back["spec"]["startupTaints"][0]["key"] == "s"
    assert back["spec"]["ttlSecondsAfterEmpty"] == 30
    assert back["spec"]["weight"] == 10


@requires_cryptography
def test_default_patch_is_per_key_and_preserves_unknown_fields(server):
    """The mutating patch touches only keys defaulting changed — canonical
    vs canonical comparison, so wire canonicalization (camelCase, quantity
    strings) and unknown spec fields never produce or lose data."""
    _, _, port = server
    obj = {
        "kind": "Provisioner",
        "metadata": {"name": "p"},
        "spec": {
            "provider": {"fake": True},
            "limits": {"resources": {"cpu": "100"}},  # string quantity
            "somethingUnknown": {"keep": "me"},  # not in the model
        },
    }
    out = _post(port, "/default", _review("Provisioner", obj))
    resp = out["response"]
    assert resp["allowed"] is True
    if "patch" in resp:
        patch = json.loads(base64.b64decode(resp["patch"]))
        for op in patch:
            # per-key ops only; never a whole-spec replace that would drop
            # the unknown field, and never a rewrite of untouched keys
            assert op["path"].startswith("/spec/")
            assert op["path"] != "/spec/somethingUnknown"
            assert op["path"] != "/spec/limits"
