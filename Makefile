# Developer entry points — the analog of the reference's Makefile targets
# (test/deflake/verify, reference Makefile:9-33). Tests force the CPU
# backend with 8 virtual devices via tests/conftest.py.

.PHONY: test deflake perf bench verify trace-demo

test:  ## full suite (CPU, 8 virtual devices)
	python -m pytest tests -q

deflake:  ## until-it-fails loop over the concurrency-sensitive suites
	./hack/deflake.sh

perf:  ## enforced >=100 pods/sec floor (reference test_performance tag)
	KCT_PERF=1 python -m pytest tests/test_perf_floor.py -q

bench:  ## north-star benchmark on the attached backend (one JSON line)
	python bench.py

trace-demo:  ## small traced solve -> /tmp/karpenter_trace.json (validated)
	python hack/trace_demo.py

verify:  ## driver hooks: single-chip compile check + 8-way mesh dryrun
	# force the CPU backend in-process: this image's sitecustomize pins the
	# axon TPU tunnel (env vars can't override it), and a wedged tunnel
	# would hang the compile check forever — verify must be hermetic
	python -c "import jax; jax.config.update('jax_platforms', 'cpu'); \
	import __graft_entry__ as g; fn, a = g.entry(); \
	jax.block_until_ready(jax.jit(fn)(*a)); print('entry ok')"
	python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"
	# non-fatal smoke: a traced solve must export valid Perfetto JSON
	-$(MAKE) trace-demo
