# Developer entry points — the analog of the reference's Makefile targets
# (test/deflake/verify, reference Makefile:9-33). Tests force the CPU
# backend with 8 virtual devices via tests/conftest.py.

.PHONY: test deflake perf bench verify trace-demo chaos chaos-smoke \
	replay-demo lint irlint soak soak-smoke soak-smoke-inproc prewarm-smoke \
	multichip-smoke consolidation-smoke bench-smoke host-smoke race-smoke \
	segment-smoke obs-smoke prof-smoke

test:  ## tier-1 suite (CPU, 8 virtual devices); slow chaos soaks: make chaos
	python -m pytest tests -q -m "not slow"

deflake:  ## until-it-fails loop over the concurrency-sensitive suites
	./hack/deflake.sh

perf:  ## enforced >=100 pods/sec floor (reference test_performance tag)
	KCT_PERF=1 python -m pytest tests/test_perf_floor.py -q

bench:  ## north-star benchmark on the attached backend (one JSON line)
	python bench.py

trace-demo:  ## small traced solve -> /tmp/karpenter_trace.json (validated)
	python hack/trace_demo.py

replay-demo:  ## flight-recorded solve -> dump -> byte-identical replay
	python hack/replay.py --demo

lint:  ## static analysis, all passes (rule catalog: docs/static-analysis.md)
	python hack/lint.py

irlint:  ## IR contract sweep: stage the compiled-program family on CPU and
	# check jaxpr/HLO contracts (rule ids ir-*; catalog in
	# analysis/irlint/contracts.py, docs in docs/static-analysis.md).
	# Warm (persistent compile cache) this stays under ~2 minutes.
	# Non-fatal in verify, FATAL in hack/presubmit.sh.
	python hack/lint.py --ir

race-smoke:  ## the -race gate at full depth: lock-heavy suites, racewatch exhaustive
	# sampling off + per-field access cap disabled (tier-1 runs the same
	# detector with default bounds; this lane trades speed for depth).
	# Non-fatal in verify, FATAL in hack/presubmit.sh.
	KARPENTER_RACEWATCH=1 KARPENTER_RACEWATCH_SAMPLE=1 KARPENTER_RACEWATCH_CAP=0 \
	python -m pytest tests/test_solver_host.py tests/test_resilient_recovery.py \
		tests/test_supervise.py tests/test_racewatch.py \
		tests/test_admission_fairshare.py -q

chaos:  ## fault-injection suite (incl. slow schedule cases), fixed seed
	KARPENTER_CHAOS_SEED=42 python -m pytest \
		tests/test_chaos_registry.py tests/test_chaos_operator.py \
		tests/test_chaos_solver.py tests/test_kube_retry.py \
		tests/test_resilient_recovery.py -q

chaos-smoke:  ## env-spec chaos run -> loop recovers + counters exposed
	python hack/chaos_smoke.py

soak:  ## >=60s sustained-churn soak, chaos armed + flightrec on (CPU-hermetic;
	# override the backend by exporting JAX_PLATFORMS before calling)
	JAX_PLATFORMS=$${JAX_PLATFORMS:-cpu} python hack/soak.py

soak-smoke:  ## <=30s seeded churn smoke through the solver HOST (CI gate: admission
	# SLOs + delta re-solve in the child + crash-drill respawn + overload shed)
	JAX_PLATFORMS=$${JAX_PLATFORMS:-cpu} python hack/soak.py --smoke --host

soak-smoke-inproc:  ## the KARPENTER_SOLVER_HOST=off posture's wedge drill: in-process
	# hang -> heartbeat-stale abandon -> breaker -> prober-gated re-admit
	JAX_PLATFORMS=$${JAX_PLATFORMS:-cpu} python hack/soak.py --smoke

host-smoke:  ## kill the solver host mid-solve under the live operator: wedge + crash
	# drills -> respawn, byte-identical parity, zero live zombies (~60s budget)
	JAX_PLATFORMS=$${JAX_PLATFORMS:-cpu} python hack/host_smoke.py

obs-smoke:  ## cross-process observability on a live host-mode operator: child
	# device phases grafted into /debug/trace (set parity), merged metrics
	# under the process label + trace-id exemplars, wedge kill names the phase
	JAX_PLATFORMS=$${JAX_PLATFORMS:-cpu} python hack/obs_smoke.py

prof-smoke:  ## compiled-program cost inventory + perf ledger on a live host-mode
	# operator: /debug/programs unifies child + local entries with compile
	# seconds, a chaos-wedged probe's forensics name the init phase, and a
	# two-round PERF_LEDGER.json tripwires a seeded 2x slowdown
	JAX_PLATFORMS=$${JAX_PLATFORMS:-cpu} python hack/prof_smoke.py

prewarm-smoke:  ## warm-cache restart gate: prewarm a tier, restart fresh, first solve under budget
	JAX_PLATFORMS=$${JAX_PLATFORMS:-cpu} python hack/prewarm_smoke.py

multichip-smoke:  ## virtual 8-device GSPMD parity (byte-identical) + speedup sanity
	python hack/multichip_smoke.py

consolidation-smoke:  ## batched subset evaluator vs sequential simulator on a live operator
	python hack/consolidation_smoke.py

bench-smoke:  ## tiny CPU resumable round: chaos-wedged stage degrades, --resume backfills
	python hack/bench_smoke.py

segment-smoke:  ## segmented pack scan on a live operator: byte-identical to
	# sequential, fixup fraction reported, chaos degrades segmented->sequential
	python hack/segment_smoke.py

verify:  ## driver hooks: single-chip compile check + 8-way mesh dryrun
	# force the CPU backend in-process: this image's sitecustomize pins the
	# axon TPU tunnel (env vars can't override it), and a wedged tunnel
	# would hang the compile check forever — verify must be hermetic
	python -c "import jax; jax.config.update('jax_platforms', 'cpu'); \
	import __graft_entry__ as g; fn, a = g.entry(); \
	jax.block_until_ready(jax.jit(fn)(*a)); print('entry ok')"
	python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"
	# static analysis (fatal): all passes, empty baseline, no suppressions
	$(MAKE) lint
	# non-fatal: IR contract sweep over the staged compiled-program family
	# (jaxpr/HLO budgets; fatal gate lives in presubmit — a cold compile
	# cache can push this past verify's time budget)
	-$(MAKE) irlint
	# the -race gate's own suites (fatal): the three ISSUE 13 passes'
	# good/bad fixtures, the sarif/changed/parallel driver modes, the
	# self-lint zero-violation wall, and the lockwatch/racewatch canaries
	# (seeded deadlock cycle + seeded data race must be DETECTED)
	python -m pytest tests/test_analysis_framework.py \
		tests/test_analysis_passes.py tests/test_self_lint.py \
		tests/test_lockwatch.py tests/test_racewatch.py -q
	# metrics-scraper suite: the scrape-race/startup-guard regressions
	python -m pytest tests/test_metrics_controllers.py -q
	# pack-kernel structural tripwires (fatal): the prescreen scan body
	# must not re-grow the full-width slot-screen contraction, the
	# precompute must stay inside the 2-programs-per-geometry cache budget,
	# and the batched consolidation evaluator's Commands must pass
	# sequential-simulator validation (test_consolidation_parity)
	python -m pytest tests/test_perf_floor.py tests/test_screen_parity.py \
		tests/test_consolidation_parity.py -q
	# wedge-proof supervisor + resumable stage-graph bench (fatal): heartbeat
	# staleness vs slow, atomic artifact resume, process-group kill, and the
	# plan/merge graph over a fake round dir (ISSUE 11)
	python -m pytest tests/test_supervise.py tests/test_bench_resume.py -q
	# fair-share admission (fatal, ISSUE 17): WFQ/EDF dispatch order,
	# per-tenant quota + retry-after isolation, the retry budget, the
	# burn-driven brownout ladder's hysteresis, and the miniature
	# two-tenant flood drill
	python -m pytest tests/test_admission_fairshare.py \
		tests/test_tenant_attribution.py -q
	# non-fatal smoke: a traced solve must export valid Perfetto JSON
	-$(MAKE) trace-demo
	# non-fatal smoke: a flight-recorded solve must replay byte-identically
	-$(MAKE) replay-demo
	# non-fatal smoke: an env-spec chaos run must recover and expose the
	# karpenter_chaos_injected_total / retry / ICE counters
	-$(MAKE) chaos-smoke
	# non-fatal smoke: a short seeded churn soak must bind every pod and
	# engage the incremental delta re-solve (fatal gate lives in presubmit);
	# host mode + the in-process wedge-drill posture both stay covered
	-$(MAKE) soak-smoke
	-$(MAKE) soak-smoke-inproc
	# non-fatal smoke: a prewarmed persistent cache must make a restarted
	# process's first solve fast (fatal gate lives in presubmit)
	-$(MAKE) prewarm-smoke
	# non-fatal smoke: GSPMD mesh parity (byte-identical placements) +
	# multichip speedup sanity on 8 virtual devices (fatal in presubmit)
	-$(MAKE) multichip-smoke
	# non-fatal smoke: the batched consolidation evaluator must pick a
	# command the sequential simulator validates, live and in offline
	# replay (fatal gate lives in presubmit)
	-$(MAKE) consolidation-smoke
	# non-fatal smoke: a chaos-wedged bench stage must degrade to a marked
	# column and --resume must backfill it (fatal gate lives in presubmit)
	-$(MAKE) bench-smoke
	# non-fatal smoke: the solver host killed mid-solve must respawn with
	# byte-identical placements and zero live zombies (fatal in presubmit)
	-$(MAKE) host-smoke
	# non-fatal smoke: host-mode /debug/trace must carry the child's grafted
	# device phases, the exposition the merged child metrics + exemplars,
	# and a chaos-killed child a phase-named wedge event (fatal in presubmit)
	-$(MAKE) obs-smoke
	# non-fatal smoke: /debug/programs must unify the sidecar child's
	# compiled-program inventory with the local one, a wedged probe must
	# name its init phase, and the perf-ledger tripwire must catch a
	# seeded slowdown (fatal in presubmit)
	-$(MAKE) prof-smoke
	# non-fatal smoke: the segmented pack scan on a live operator must stay
	# byte-identical to sequential and degrade cleanly under chaos (fatal
	# gate lives in presubmit)
	-$(MAKE) segment-smoke
	# non-fatal smoke: the lock-heavy suites under the exhaustive racewatch
	# posture — sampling off, cap off (fatal gate lives in presubmit)
	-$(MAKE) race-smoke
