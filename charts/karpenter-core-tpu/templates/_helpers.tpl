{{- define "karpenter.name" -}}
{{- default .Chart.Name .Values.nameOverride | trunc 63 | trimSuffix "-" -}}
{{- end -}}

{{- define "karpenter.labels" -}}
app.kubernetes.io/name: {{ include "karpenter.name" . }}
app.kubernetes.io/instance: {{ .Release.Name }}
app.kubernetes.io/version: {{ .Chart.AppVersion }}
{{- end -}}

{{- define "karpenter.serviceAccountName" -}}
{{- if .Values.serviceAccount.create -}}
{{- default (include "karpenter.name" .) .Values.serviceAccount.name -}}
{{- else -}}
{{- default "default" .Values.serviceAccount.name -}}
{{- end -}}
{{- end -}}
